#!/usr/bin/env python
"""chaos_drill: run a small distributed training job under a named fault
scenario and exit nonzero unless recovery succeeds.

    python tools/chaos_drill.py --scenario pserver_kill [--seed 7]

Scenarios (all seed-deterministic through ark.chaos):

    flaky_rpc     connections randomly die and stall under the trainer;
                  PASS = training completes, converges, and the retry
                  counters show the client actually recovered
    quant_flaky_rpc  int8-quantized sync-PS pushes (fluid-wire) under
                  close/truncate/delay chaos with batch retries; PASS =
                  the final params are BIT-IDENTICAL to the no-fault
                  quantized run (replayed frames dedup server-side and
                  the error-feedback residual commits exactly once per
                  logical batch — never double-applied on replay)
    pserver_kill  SIGKILL-equivalent pserver death mid-run; PASS = the
                  restarted server recovers its atomic shard checkpoint
                  and the run finishes inside the no-fault loss band
    ckpt_crash    a crash is injected mid-`save_checkpoint` (the commit
                  rename never happens); PASS = the previous serial
                  loads intact (manifest checksums verify) and a fresh
                  trainer auto-resumes bit-identically
    sync_evict    a sync trainer dies holding a heartbeat lease; PASS =
                  the barrier evicts it in lease-time (not sync_timeout)
                  and the surviving trainer's update applies once
    dist_trace    a REAL 2-process trainer+pserver job (tools/
                  ps_worker.py is the server process) killed by SIGTERM
                  mid-run; PASS = the dead server left BOTH postmortem
                  artifacts (chrome trace + flight-recorder JSON) and
                  the merged timeline links client and server RPC spans
                  under one trace id across the two processes
    health_alerts a live 2-process job with fluid-pulse armed on both
                  sides; a NaN loss and a pserver SIGKILL are injected;
                  PASS = the trainer's /healthz flips to 503/unready
                  with the expected alerts (non_finite_loss,
                  ps_retry_storm) and the flight dump records both
                  alerts with the triggering series' last points
    replica_kill  fluid-fleet: one of three serving replica PROCESSES is
                  SIGKILLed under open-loop router traffic; PASS = zero
                  failed requests (failovers metered; p99 degrades and
                  is recorded), the dead replica's lease expires, and
                  the survivors show zero steady-state recompiles
    decode_kill   fluid-torrent: one of two DECODE replica processes of
                  a disaggregated (1 prefill + 2 decode) fleet is
                  SIGKILLed under concurrent generative traffic; PASS =
                  every generation completes and is TOKEN-IDENTICAL to
                  the solo no-fault reference (pinned sequences fail
                  over via re-prefill; greedy decoding is deterministic
                  so zero completed tokens are lost), torrent failovers
                  metered, every session pin released, and the dead
                  replica's lease expires
    ps_primary_kill  fluid-haven: SIGKILL the PRIMARY of a replicated
                  pserver pair mid-training, under async AND sync PS;
                  PASS = training completes with zero trainer-visible
                  failures, the no-fault replicated run is BIT-IDENTICAL
                  to the unreplicated baseline, final loss lands inside
                  the bounded-loss band, the promotion is metered, and
                  the surviving backup's flight recorder shows the
                  promotion event
    ps_handover   fluid-haven: planned live shard handoff to a fresh
                  standby under continuous training load; PASS = zero
                  failed trainer steps, exactly ONE lease-holder at
                  every sampled instant, exact update continuity across
                  the flip, and the handover promotion metered
    master_kill   fluid-elastic: SIGKILL the PRIMARY data master of a
                  quorum-armed HA pair while consumers stream records;
                  PASS = the standby promotes inside the lease budget,
                  zero consumer-visible failures (stall bounded by the
                  blip), at most ONE task-issuing master at every 5ms
                  sample, every record delivered with exactly-once
                  accounting (single-issue tasks delivered exactly
                  once; duplicates only from failure-budget re-issues)
    master_partition  fluid-elastic: the primary master is cut from its
                  standby and from 2/3 arbiters (it keeps the minority)
                  while consumers reach everyone; PASS = the minority
                  primary fences then steps down (its stale replies are
                  redirects, never mutations), the majority-side standby
                  promotes, consumers follow the quorum holder, at most
                  one issuing master at every sample, exactly-once
                  accounting as in master_kill
    trainer_churn fluid-elastic scale-down AND scale-UP: 3 sync-PS
                  trainers stream master-leased batches; one is killed
                  mid-pass (world degrades 3→2 in lease-time) and a
                  REPLACEMENT with a fresh trainer id is started mid-job
                  (admitted at the next barrier epoch, world 2→3, pulls
                  current params before its first push); PASS = world
                  size observed 3→2→3, every record processed exactly
                  once up to the failure-budget re-issue, final loss in
                  the no-fault band, zero trainer-visible failures
    ps_partition  fluid-quorum: ASYMMETRIC partition of a quorum-armed
                  haven pair under async AND sync PS — the primary is
                  cut from its backup and from a majority of the three
                  arbiters while the backup keeps the majority; PASS =
                  at most one write-acceptor at every 5ms sample, the
                  majority side promotes within the lease budget, the
                  minority primary fences and steps down (epoch-stale
                  writes rejected, not applied), zero trainer-visible
                  failures, bounded loss, and the healed node rejoins
                  as a resyncing standby with zero lost acked updates

`--trace-out DIR` (any scenario): every participating process writes its
chrome trace file into DIR (`trace_<process>.json`) and the drill merges
them into `DIR/merged_trace.json`; the drill FAILS if the merge drops
spans. This is the fluid-xray "one coherent picture of a chaos drill"
artifact — open the merged file in chrome://tracing or perfetto.

The CI wrapper (`tests/test_fault_tolerance.py::test_chaos_drill_cli`)
is marked `slow`, so tier-1 wall time is unaffected; run the drills
explicitly with `pytest -m slow tests/test_fault_tolerance.py` or this
CLI.
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import ark, layers  # noqa: E402
from paddle_tpu.ark import chaos  # noqa: E402
from paddle_tpu.observe import metrics as obs_metrics  # noqa: E402
from paddle_tpu.pserver import (AsyncPSTrainer, ParameterServer,  # noqa: E402
                                PSClient)


class DrillFailure(Exception):
    pass


def _check(ok, what):
    status = "ok" if ok else "FAIL"
    print(f"  [{status}] {what}")
    if not ok:
        raise DrillFailure(what)


def _fresh_world(seed, n_servers=2, lr=0.1):
    servers = [ParameterServer("127.0.0.1:0").start()
               for _ in range(n_servers)]
    eps = ",".join(s.endpoint for s in servers)
    tr, loss, batch = _build_world(eps, seed, lr=lr)
    return servers, tr, loss, batch


def _build_world(eps, seed, lr=0.1, sync=False, haven_replicas=None,
                 quorum_endpoints=None, quorum_resources=None):
    """Trainer half of the 2-layer FC world, against endpoints that may
    live in ANOTHER process (the health_alerts drill's ps_worker).
    `sync=True` builds the pserver-runtime sync world (SyncPSTrainer);
    `haven_replicas` arms the client's primary re-resolution + tagged
    pushes for the fluid-haven drills; `quorum_endpoints`/`_resources`
    give the client the arbiters' view of who rules a shard
    (fluid-quorum)."""
    from paddle_tpu.pserver import SyncPSTrainer

    np.random.seed(seed)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="int64")
        h = layers.fc(input=x, size=16, act="relu")
        logits = layers.fc(input=h, size=2, act=None)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    main.random_seed = startup.random_seed = seed
    cfg = fluid.DistributeTranspilerConfig()
    if sync:
        cfg.runtime = "pserver"
    if haven_replicas:
        cfg.haven_replicas = dict(haven_replicas)
    if quorum_endpoints:
        cfg.quorum_endpoints = list(quorum_endpoints)
        cfg.quorum_resources = dict(quorum_resources or {})
    t = fluid.DistributeTranspiler(cfg)
    t.transpile(trainer_id=0, program=main, pservers=eps, trainers=1,
                sync_mode=sync)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    cls = SyncPSTrainer if sync else AsyncPSTrainer
    tr = cls(t, exe, program=main, scope=scope)
    tr.init_params()
    rng = np.random.RandomState(seed + 1)
    w_true = rng.randn(8, 2).astype(np.float32)

    def batch(n=32):
        xs = rng.randn(n, 8).astype(np.float32)
        ys = (xs @ w_true).argmax(1).astype(np.int64).reshape(n, 1)
        return {"x": xs, "y": ys}

    return tr, loss, batch


def _run_steps(tr, loss, batch, n):
    out = []
    for _ in range(n):
        l, = tr.step(batch(), fetch_list=[loss])
        out.append(float(np.asarray(l).reshape(-1)[0]))
    return out


def drill_flaky_rpc(seed, workdir, trace_out=None):
    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    servers, tr, loss, batch = _fresh_world(seed)
    try:
        with chaos.ChaosMonkey(seed=seed, p_close=0.06, p_delay=0.06,
                               delay_s=(0.001, 0.02)) as monkey:
            losses = _run_steps(tr, loss, batch, 30)
        _check(monkey.total_injected() > 0,
               f"faults injected ({monkey.injected})")
        _check(np.isfinite(losses).all(), "all losses finite")
        _check(np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.8,
               f"converged {np.mean(losses[:5]):.3f} -> "
               f"{np.mean(losses[-5:]):.3f}")
        retries = obs_metrics.default_registry().get(
            "pserver_client_retries_total")
        _check(retries is not None and retries.total() >= 1,
               f"retries recorded "
               f"({retries.total() if retries else 0:.0f})")
        tr.close()
    finally:
        fluid.set_flag("observe", False)
        for s in servers:
            s.stop()


def drill_pserver_kill(seed, workdir, trace_out=None):
    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    # no-fault reference band
    servers, tr, loss, batch = _fresh_world(seed)
    try:
        ref = _run_steps(tr, loss, batch, 30)
        tr.close()
    finally:
        for s in servers:
            s.stop()

    servers, tr, loss, batch = _fresh_world(seed)
    try:
        losses = _run_steps(tr, loss, batch, 12)
        ckpt = os.path.join(workdir, "shards")
        tr.save(ckpt)
        for s in servers:
            ark.verify_sidecar(s._shard_path(ckpt))
        print(f"  shards checkpointed to {ckpt} (manifests verified)")

        victim = chaos.kill_server(servers[1])
        print(f"  killed pserver {victim} mid-epoch")
        time.sleep(0.1)
        servers[1] = chaos.restart_server(victim, recover_dir=ckpt)
        print(f"  restarted {victim}, shard recovered")

        losses += _run_steps(tr, loss, batch, 18)
        _check(np.isfinite(losses).all(), "all losses finite")
        band = np.mean(ref[-6:]) * 1.25 + 0.05
        _check(np.mean(losses[-6:]) < band,
               f"final loss {np.mean(losses[-6:]):.4f} within no-fault "
               f"band (<{band:.4f})")
        retries = obs_metrics.default_registry().get(
            "pserver_client_retries_total")
        print(f"  client retries: "
              f"{retries.total() if retries else 0:.0f}")
        tr.close()
    finally:
        fluid.set_flag("observe", False)
        for s in servers:
            s.stop()


def drill_ckpt_crash(seed, workdir, trace_out=None):
    d = os.path.join(workdir, "ck")
    arrays = {"w": np.arange(12, dtype=np.float32)}
    ark.save_checkpoint(d, arrays, cursor={"step_id": 1},
                        rng={"train_runs": 1})
    good = ark.latest_checkpoint(d)

    # crash inside the save, after files are staged but before commit
    class Crash(Exception):
        pass

    def dying_shard_saver(stage):
        with open(os.path.join(stage, "shard.bin"), "wb") as f:
            f.write(b"half-written shard")
        raise Crash("process died mid-save")

    try:
        ark.save_checkpoint(d, {"w": arrays["w"] * 2},
                            cursor={"step_id": 2},
                            shard_saver=dying_shard_saver)
    except Crash:
        print("  crash injected mid-save_checkpoint")
    _check(ark.latest_checkpoint(d) == good,
           "previous serial is still the newest committed one")
    ark.verify_checkpoint(good)
    print("  previous serial verifies (manifest checksums)")
    got, manifest = ark.load_checkpoint(good)
    _check(np.array_equal(got["w"], arrays["w"]) and
           manifest["cursor"]["step_id"] == 1,
           "previous checkpoint loads intact")


def drill_sync_evict(seed, workdir, trace_out=None):
    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    srv = ParameterServer("127.0.0.1:0", trainers=2,
                          sync_timeout=120.0).start()
    ep = srv.endpoint
    c = PSClient([ep])
    try:
        c.init_param(ep, "w", np.zeros(3, np.float32), "sgd", 1.0, {})
        c.heartbeat(ep, trainer_id=1, session="doomed", lease_s=0.5)
        print("  trainer 1 held a 0.5s lease, then died")
        time.sleep(0.8)
        c.push_grads_sync({ep: {"w": np.full(3, 2.0, np.float32)}},
                          batch_id=0, trainer_id=0, session="alive")
        t0 = time.monotonic()
        c.sync_apply([ep])
        dt = time.monotonic() - t0
        _check(dt < 10.0, f"barrier released in {dt:.2f}s "
                          f"(sync_timeout=120s)")
        _check(np.allclose(c.get_param(ep, "w"), -2.0),
               "survivor's update applied once, averaged over live world")
        evicted = obs_metrics.default_registry().get(
            "pserver_trainers_evicted_total")
        _check(evicted is not None and evicted.total() == 1,
               "eviction metered")
        c.close()
    finally:
        fluid.set_flag("observe", False)
        srv.stop()


def drill_quant_flaky_rpc(seed, workdir, trace_out=None):
    """fluid-wire: truncated/retried QUANTIZED frames recover BIT-SAFELY.

    Two sync-PS runs push the same int8-quantized gradient sequence with
    error feedback — one clean, one under chaos (close / truncate-mid-
    frame / delay) with caller-level batch retries. The final server
    params must be BIT-IDENTICAL: transport retries resend the same
    encoded bytes, the server dedups replayed batches by (trainer,
    batch, session), and the client's error-feedback residual commits
    exactly once per logical batch (a replay never double-applies it)."""
    from paddle_tpu.wire import ENCODED_BYTES_METRIC, RAW_BYTES_METRIC

    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    STEPS = 25
    rng = np.random.RandomState(seed)
    # odd length: the last int8 chunk is partial, so the padded tail of
    # the codec is exercised on every frame
    grads = [(rng.randn(257) * 0.1).astype(np.float32)
             for _ in range(STEPS)]

    def run(monkey=None):
        srv = ParameterServer("127.0.0.1:0", trainers=1).start()
        try:
            c = PSClient([srv.endpoint], comm_quant="int8")
            c.init_param(srv.endpoint, "w", np.zeros(257, np.float32),
                         "sgd", lr=0.5, attrs={})
            retried = 0
            # Negotiate wire_caps BEFORE chaos starts: the lazy one-shot
            # negotiation inside the first push would otherwise run under
            # fault injection, and an exhausted-retry ConnectionError
            # caches raw for the endpoint — the whole run would push
            # float32 and fail the bit-identity check for a reason
            # unrelated to the replay contract this drill proves.
            if c._codec_for(srv.endpoint) != "int8":
                raise DrillFailure("wire_caps negotiation did not land "
                                   "on int8 before chaos")
            if monkey is not None:
                monkey.start()
            try:
                for i, g in enumerate(grads):
                    for _ in range(30):
                        try:
                            c.push_grads_sync(
                                {srv.endpoint: {"w": g}}, batch_id=i,
                                trainer_id=0, session="drill")
                            c.sync_apply([srv.endpoint])
                            break
                        except (RuntimeError, ConnectionError, OSError,
                                EOFError):
                            retried += 1
                    else:
                        raise DrillFailure(f"batch {i} never applied")
            finally:
                if monkey is not None:
                    monkey.stop()
            final = np.array(c.get_param(srv.endpoint, "w"))
            c.close()
            return final, retried
        finally:
            srv.stop()

    try:
        ref, _ = run()
        print(f"  no-fault quantized run complete ({STEPS} batches)")
        reg = obs_metrics.default_registry()
        raw = reg.get(RAW_BYTES_METRIC).value(cmd="push_grads_sync")
        enc = reg.get(ENCODED_BYTES_METRIC).value(cmd="push_grads_sync")
        _check(enc < 0.5 * raw,
               f"quantized frames on the wire ({raw:.0f} -> {enc:.0f} "
               f"bytes, {raw / enc:.2f}x)")

        monkey = chaos.ChaosMonkey(seed=seed, p_close=0.05,
                                   p_truncate=0.05, p_delay=0.05,
                                   delay_s=(0.001, 0.01))
        got, retried = run(monkey)
        _check(monkey.total_injected() > 0,
               f"faults injected ({monkey.injected})")
        _check(monkey.injected["truncate"] + monkey.injected["close"] > 0,
               "at least one frame died mid-flight")
        retries = obs_metrics.default_registry().get(
            "pserver_client_retries_total")
        transport_retries = retries.total() if retries else 0
        _check(transport_retries + retried >= 1,
               f"frames actually replayed (transport retries "
               f"{transport_retries:.0f}, batch retries {retried})")
        _check(np.array_equal(got, ref),
               "chaos run BIT-IDENTICAL to the no-fault quantized run "
               "(error-feedback residual never double-applied on replay)")
    finally:
        fluid.set_flag("observe", False)


def drill_dist_trace(seed, workdir, trace_out=None):
    """2-process trainer+pserver job under SIGTERM (fluid-xray)."""
    import json
    import signal
    import subprocess

    from paddle_tpu.observe import xray

    out = trace_out or workdir
    os.makedirs(out, exist_ok=True)
    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    xray.set_process_name("trainer0")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "ps_worker.py")
    proc = subprocess.Popen(
        [sys.executable, worker, "--name", "pserver0", "--out", out],
        stdout=subprocess.PIPE, text=True, env=env)
    client = None
    try:
        line = (proc.stdout.readline() or "").strip()
        _check(line.startswith("ENDPOINT "), f"server process up ({line})")
        ep = line.split()[1]
        client = PSClient([ep])
        client.init_param(ep, "w", np.zeros(4, np.float32), "sgd", 0.1, {})
        for _ in range(3):
            client.push_grad(ep, "w", np.full(4, 0.1, np.float32))
        client.heartbeat(ep, trainer_id=0, session="drill")
        got = client.get_param(ep, "w")
        _check(np.isfinite(np.asarray(got)).all(),
               "RPCs served across processes")

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        print(f"  SIGTERM'd pserver process (rc={rc})")
        # the dying server must have left BOTH artifacts
        _check(os.path.exists(os.path.join(out, "trace_pserver0.json")),
               "server chrome trace dumped on SIGTERM")
        fr_path = os.path.join(out, "flight_pserver0.json")
        _check(os.path.exists(fr_path), "server flight recorder dumped")
        with open(fr_path) as f:
            fr = json.load(f)
        _check(str(fr.get("reason", "")).startswith("signal"),
               f"flight dump names the killer ({fr.get('reason')})")
        _check(any(e.get("kind") == "signal" for e in fr["events"]),
               "flight ring recorded the TERM")
        # one post-kill call: its retries put fail_connect attempt spans
        # (same trace id, distinct span ids) on the trainer timeline
        try:
            client.get_param(ep, "w")
        except Exception:
            pass
    finally:
        if client is not None:
            client.close()
        if proc.poll() is None:
            proc.kill()
        fluid.set_flag("observe", False)


def drill_health_alerts(seed, workdir, trace_out=None):
    """fluid-pulse: a live 2-process job whose health plane must catch a
    NaN loss and a pserver death WHILE RUNNING — before any postmortem.

    A real trainer (this process, pulse armed) drives a real ps_worker
    subprocess (pulse armed too). PASS requires: both /healthz
    endpoints answer ok pre-fault; injecting a NaN batch flips the
    trainer's /healthz to HTTP 503/unready with a `non_finite_loss`
    alert; SIGKILLing the pserver raises a `ps_retry_storm` alert; and
    the trainer's flight-recorder dump carries both alert records with
    the last points of the triggering series — the endpoint and the
    black box agree on why health went red."""
    import json
    import subprocess
    import urllib.error
    import urllib.request

    from paddle_tpu.observe import flight, health, pulse

    def get(port, path):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    health.reset()
    local_port = pulse.start_pulse(0)
    print(f"  trainer pulse on port {local_port}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "ps_worker.py")
    proc = subprocess.Popen(
        [sys.executable, worker, "--name", "pserver0", "--out", workdir,
         "--pulse-port", "0"],
        stdout=subprocess.PIPE, text=True, env=env)
    tr = None
    try:
        line = (proc.stdout.readline() or "").strip()
        _check(line.startswith("ENDPOINT "), f"server process up ({line})")
        ep = line.split()[1]
        line = (proc.stdout.readline() or "").strip()
        _check(line.startswith("PULSE "), f"server pulse up ({line})")
        srv_pulse = int(line.split()[1])
        code, doc = get(srv_pulse, "/healthz")
        _check(code == 200 and doc["status"] == "ok",
               f"server /healthz ok pre-fault "
               f"(checks: {sorted(doc['checks'])})")

        tr, loss, batch = _build_world(ep, seed)
        losses = _run_steps(tr, loss, batch, 8)
        _check(np.isfinite(losses).all(), "8 healthy steps against the "
               "remote pserver")
        code, doc = get(local_port, "/healthz")
        _check(code == 200 and doc["status"] == "ok",
               "trainer /healthz ok pre-fault")

        bad = batch()
        bad["x"][:] = np.nan
        tr.step(bad, fetch_list=[loss])
        code, doc = get(local_port, "/healthz")
        rules = {a["rule"] for a in doc["alerts"]}
        _check(code == 503 and doc["status"] == "unready",
               f"/healthz flipped unready on the NaN loss (HTTP {code})")
        _check("non_finite_loss" in rules,
               f"non-finite alert fired ({sorted(rules)})")

        proc.kill()
        proc.wait(timeout=30)
        print("  SIGKILL'd the pserver process mid-run")
        for _ in range(3):
            try:
                tr.step(batch(), fetch_list=[loss])
            except Exception:
                pass   # retries against the corpse are the point
        code, doc = get(local_port, "/healthz")
        rules = {a["rule"] for a in doc["alerts"]}
        _check("ps_retry_storm" in rules,
               f"retry-storm alert fired ({sorted(rules)})")
        _check(code == 503, "trainer /healthz still unready")

        fp = flight.dump(os.path.join(workdir, "flight_trainer0.json"),
                         reason="health_alerts drill")
        with open(fp) as f:
            fr = json.load(f)
        alert_evs = [e for e in fr["events"] if e.get("kind") == "alert"]
        got = {e["rule"] for e in alert_evs}
        _check({"non_finite_loss", "ps_retry_storm"} <= got,
               f"flight ring recorded both alerts ({sorted(got)})")
        _check(any(e.get("points") for e in alert_evs),
               "alert records carry the triggering series' last points")
        _check("memory" in fr, "flight dump carries the memory section")
    finally:
        if tr is not None:
            try:
                tr.close()
            except Exception:
                pass
        if proc.poll() is None:
            proc.kill()
        pulse.stop_pulse()
        health.reset()
        fluid.set_flag("observe", False)


def drill_replica_kill(seed, workdir, trace_out=None):
    """fluid-fleet: SIGKILL one of three serving replicas mid-traffic.

    PASS requires: zero FAILED requests (the kill's in-flight and
    subsequent dispatches fail over to live replicas — availability is
    preserved, p99 degrades and is recorded), router failovers metered,
    the dead replica's membership lease expires (it stops renewing),
    and the survivors keep serving with zero steady-state recompiles.
    Emits a JSON line (fleet_p99_pre_kill_us / fleet_p99_post_kill_us /
    fleet_kill_failed) that bench.py's `fleet` segment records."""
    import json
    import random
    import signal
    import threading

    from paddle_tpu import fleet
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fleet_router import spawn_replicas
    from serve_loadgen import build_and_save

    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    mdir = os.path.join(workdir, "model")
    build_and_save(fluid, np, mdir)
    # poll_interval 0.5: wide enough that the victim is still marked
    # ready when the post-kill burst below lands (the failover path,
    # not the poller, must be what saves those requests)
    router = fleet.FleetRouter(fleet.RouterConfig(
        lease_s=1.0, poll_interval_s=0.5)).start()
    workers = []
    try:
        workers = spawn_replicas(3, mdir, router.control_endpoint,
                                 device_ms=2.0, lease_s=1.0)
        deadline = time.time() + 60
        while len(router.ready_members("m")) < 3:
            if time.time() > deadline:
                raise DrillFailure("fleet never became ready")
            time.sleep(0.1)
        print("  3 replica processes ready behind the router")

        DURATION, QPS, THREADS = 6.0, 90.0, 6
        stop = threading.Event()
        lock = threading.Lock()
        failures, rejected, lats = [], [0], []   # (t, us)
        kill_at = [None]

        def client(tid):
            r = random.Random(seed * 100 + tid)
            lam = QPS / THREADS
            nxt = time.perf_counter()
            while not stop.is_set():
                nxt += r.expovariate(lam)
                d = nxt - time.perf_counter()
                if d > 0:
                    time.sleep(d)
                t0 = time.perf_counter()
                feed = {"x": np.random.randn(
                    r.randint(1, 4), 16).astype(np.float32)}
                try:
                    router.infer("m", feed)
                except Exception as e:      # noqa: BLE001
                    with lock:
                        if getattr(e, "retriable", False):
                            rejected[0] += 1
                        else:
                            failures.append(repr(e))
                    continue
                with lock:
                    lats.append((time.perf_counter(),
                                 (time.perf_counter() - t0) * 1e6))

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(THREADS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(DURATION / 2)
        victim = workers[1]
        kill_at[0] = time.perf_counter()
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)
        print("  SIGKILL'd replica r1 mid-traffic")
        # deterministic failover exposure: a tight burst INSIDE the poll
        # window, while the router still believes r1 is ready — the
        # requests routed at the corpse must be saved by per-request
        # failover, not by the poller having already removed it
        for _ in range(30):
            t_b = time.perf_counter()
            try:
                router.infer("m", {"x": np.random.randn(
                    2, 16).astype(np.float32)})
            except Exception as e:      # noqa: BLE001
                with lock:
                    if getattr(e, "retriable", False):
                        rejected[0] += 1
                    else:
                        failures.append(repr(e))
                continue
            with lock:
                lats.append((time.perf_counter(),
                             (time.perf_counter() - t_b) * 1e6))
        time.sleep(DURATION / 2)
        stop.set()
        for t in threads:
            t.join(timeout=20)

        def p99(window):
            vals = sorted(us for t, us in window)
            return vals[min(len(vals) - 1,
                            int(0.99 * len(vals)))] if vals else 0.0

        pre = [(t, us) for t, us in lats if t < kill_at[0]]
        post = [(t, us) for t, us in lats if t >= kill_at[0]]
        _check(not failures,
               f"zero failed requests across the kill "
               f"({len(lats)} served, first failure: "
               f"{failures[0] if failures else None})")
        _check(len(post) > 0, f"traffic kept flowing after the kill "
                              f"({len(post)} post-kill responses)")
        fo = obs_metrics.default_registry().get("fleet_failovers_total")
        _check(fo is not None and fo.total() >= 1,
               f"failovers metered ({fo.total() if fo else 0:.0f})")
        time.sleep(2.5)   # > 2 lease periods
        mem = router.members()
        _check("r1" not in mem or not mem["r1"]["lease_live"],
               "dead replica's membership lease expired")
        recompiles = 0
        for rid in ("r0", "r2"):
            st = fleet.wire.call(router._members[rid].pool,
                                 "fleet_stats", {}, deadline_s=10.0)
            recompiles += int(st.get("unexpected_recompiles", 0))
        _check(recompiles == 0,
               "zero steady-state recompiles on the survivors")
        out = {
            "fleet_kill_failed": len(failures),
            "fleet_kill_rejected": rejected[0],
            "fleet_p99_pre_kill_us": round(p99(pre), 1),
            "fleet_p99_post_kill_us": round(p99(post), 1),
            "fleet_kill_requests_ok": len(lats),
            "fleet_kill_failovers": fo.total() if fo else 0,
        }
        print(json.dumps(out))
        print(f"  p99 {out['fleet_p99_pre_kill_us']:.0f} us pre-kill -> "
              f"{out['fleet_p99_post_kill_us']:.0f} us post-kill "
              f"(degraded, never failed)")
    finally:
        for w in workers:
            if w.poll() is None:
                w.terminate()
        for w in workers:
            try:
                w.wait(timeout=10)
            except Exception:
                w.kill()
        router.close()
        fluid.set_flag("observe", False)


def drill_decode_kill(seed, workdir, trace_out=None):
    """fluid-torrent: SIGKILL a decode replica of a disaggregated fleet
    mid-generation (see module docstring)."""
    import json
    import random
    import signal
    import threading

    from paddle_tpu import fleet, serve
    from paddle_tpu.models import tiny_lm
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fleet_router import spawn_replicas

    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    mdir = os.path.join(workdir, "model")
    tiny_lm.save_tiny_lm(mdir, kv_dtype="int8", max_slots=4,
                         block_size=4, max_context=32,
                         prefill_rows=(1, 2), prefill_seq_rungs=(8, 16))

    rng = random.Random(seed)
    prompts = [[rng.randrange(32) for _ in range(rng.randint(1, 7))]
               for _ in range(10)]
    MAX_NEW = 10

    # solo no-fault reference: the token sequences every disaggregated
    # generation must reproduce EXACTLY, kill or no kill
    solo = serve.InferenceServer(fluid.CPUPlace(), serve.ServeConfig())
    solo.add_model("m", mdir)
    ref = {i: solo.generate("m", p, max_new_tokens=MAX_NEW).tokens
           for i, p in enumerate(prompts)}
    solo.close()
    print(f"  solo reference computed ({len(ref)} prompts)")

    router = fleet.FleetRouter(fleet.RouterConfig(
        lease_s=1.0, poll_interval_s=0.5)).start()
    workers = []
    try:
        # 1 prefill + 2 decode; the decode pool simulates memory-bound
        # device time per step so generations are in flight long enough
        # for the SIGKILL to land mid-decode
        workers += spawn_replicas(
            1, mdir, router.control_endpoint, rid_prefix="p",
            lease_s=1.0, extra_args=("--role", "prefill"))
        workers += spawn_replicas(
            2, mdir, router.control_endpoint, rid_prefix="d",
            lease_s=1.0, extra_args=("--role", "decode",
                                     "--sim-decode-step-us", "20000"))
        deadline = time.time() + 120
        while len(router.ready_members("m")) < 3:
            if time.time() > deadline:
                raise DrillFailure("fleet never became ready")
            time.sleep(0.1)
        print("  1 prefill + 2 decode replica processes ready")

        DURATION, THREADS = 8.0, 4
        stop = threading.Event()
        lock = threading.Lock()
        results, failures = [], []   # (prompt_idx, tokens), repr(e)
        kill_at = [None]

        def client(tid):
            r = random.Random(seed * 100 + tid)
            while not stop.is_set():
                i = r.randrange(len(prompts))
                try:
                    res = router.generate_torrent(
                        "m", prompts[i], max_new_tokens=MAX_NEW)
                except Exception as e:      # noqa: BLE001
                    with lock:
                        failures.append(repr(e))
                    continue
                with lock:
                    results.append((i, res.tokens,
                                    kill_at[0] is not None))

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(THREADS)]
        for t in threads:
            t.start()
        time.sleep(DURATION / 2)
        victim = workers[1]          # first decode replica (d0)
        kill_at[0] = time.perf_counter()
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)
        print("  SIGKILL'd decode replica d0 mid-generation")
        time.sleep(DURATION / 2)
        stop.set()
        for t in threads:
            t.join(timeout=60)

        post = [x for x in results if x[2]]
        _check(not failures,
               f"every generation completed across the kill "
               f"({len(results)} ok, first failure: "
               f"{failures[0] if failures else None})")
        _check(len(post) > 0,
               f"traffic kept flowing after the kill ({len(post)} "
               f"post-kill generations)")
        bad = [(i, toks) for i, toks, _ in results if toks != ref[i]]
        _check(not bad,
               f"zero lost completed tokens: all {len(results)} "
               f"generations token-identical to the solo reference "
               f"(first divergence: {bad[0] if bad else None})")
        reg = obs_metrics.default_registry()
        fo = reg.get("torrent_failovers_total")
        _check(fo is not None and fo.total() >= 1,
               f"torrent failovers metered "
               f"({fo.total() if fo else 0:.0f})")
        pins = reg.get("fleet_affinity_sessions")
        _check(pins is not None and pins.value() == 0.0,
               "every session pin released")
        time.sleep(2.5)   # > 2 lease periods
        mem = router.members()
        _check("d0" not in mem or not mem["d0"]["lease_live"],
               "dead decode replica's membership lease expired")

        out = {
            "decode_kill_failed": len(failures),
            "decode_kill_generations_ok": len(results),
            "decode_kill_post_kill_ok": len(post),
            "decode_kill_failovers": fo.total() if fo else 0,
            "decode_kill_divergent": len(bad),
        }
        print(json.dumps(out))
    finally:
        for w in workers:
            if w.poll() is None:
                w.terminate()
        for w in workers:
            try:
                w.wait(timeout=10)
            except Exception:
                w.kill()
        router.close()
        fluid.set_flag("observe", False)


def _haven_pair(lease_s=1.0, auto_promote=True):
    from paddle_tpu.pserver import ParameterServer

    backup = ParameterServer("127.0.0.1:0").start()
    backup.start_standby(lease_s=lease_s, auto_promote=auto_promote)
    primary = ParameterServer("127.0.0.1:0").start()
    primary.start_replication(backup.endpoint, lease_s=lease_s)
    return primary, backup


def _final_params(tr):
    return {p: np.array(tr.client.get_param(spec["endpoint"], p))
            for p, spec in tr.t.param_specs.items()}


def drill_ps_primary_kill(seed, workdir, trace_out=None):
    """fluid-haven: SIGKILL the PRIMARY of a replicated pserver pair
    mid-training, under async and sync PS (see module docstring)."""
    from paddle_tpu.observe import flight as obs_flight

    N1, N2 = 10, 14
    for mode in ("async", "sync"):
        sync = mode == "sync"
        fluid.set_flag("observe", True)
        obs_metrics.default_registry().reset()

        # 1) unreplicated baseline: the loss band AND the bit-identity
        # reference for the no-fault replicated run
        from paddle_tpu.pserver import ParameterServer
        solo = ParameterServer("127.0.0.1:0").start()
        try:
            tr, loss, batch = _build_world(solo.endpoint, seed, sync=sync)
            ref = _run_steps(tr, loss, batch, N1 + N2)
            ref_params = _final_params(tr)
            tr.close()
        finally:
            solo.stop()

        # 2) replicated, no fault: replication must be PASSIVE —
        # bit-identical to the unreplicated baseline
        primary, backup = _haven_pair(lease_s=1.0)
        try:
            tr, loss, batch = _build_world(
                primary.endpoint, seed, sync=sync,
                haven_replicas={primary.endpoint: [backup.endpoint]})
            clean = _run_steps(tr, loss, batch, N1 + N2)
            _check(clean == ref,
                   f"[{mode}] no-fault replicated losses bit-identical "
                   f"to unreplicated baseline")
            got = _final_params(tr)
            _check(all(np.array_equal(got[p], ref_params[p])
                       for p in ref_params),
                   f"[{mode}] no-fault replicated params bit-identical")
            tr.close()
        finally:
            primary.stop()
            backup.stop()

        # 3) replicated + SIGKILL'd primary mid-run
        obs_metrics.default_registry().reset()
        primary, backup = _haven_pair(lease_s=1.0)
        try:
            tr, loss, batch = _build_world(
                primary.endpoint, seed, sync=sync,
                haven_replicas={primary.endpoint: [backup.endpoint]})
            losses = _run_steps(tr, loss, batch, N1)
            victim = chaos.kill_server(primary)
            print(f"  [{mode}] SIGKILL'd primary {victim} at step {N1}")
            t0 = time.monotonic()
            losses += _run_steps(tr, loss, batch, N2)   # raises = FAIL
            print(f"  [{mode}] {N2} post-kill steps completed "
                  f"(first blip absorbed in {time.monotonic() - t0:.1f}s "
                  f"of tail)")
            _check(np.isfinite(losses).all(),
                   f"[{mode}] all losses finite, zero trainer-visible "
                   f"failures")
            band = np.mean(ref[-6:]) * 1.25 + 0.05
            _check(np.mean(losses[-6:]) < band,
                   f"[{mode}] final loss {np.mean(losses[-6:]):.4f} "
                   f"inside the bounded-loss band (<{band:.4f})")
            _check(backup._haven.role == "primary",
                   f"[{mode}] backup promoted itself (epoch "
                   f"{backup._haven.epoch})")
            promoted = obs_metrics.default_registry().get(
                "ps_promotions_total")
            _check(promoted is not None and promoted.total() >= 1,
                   f"[{mode}] promotion metered")
            promos = obs_flight.get_flight().events("haven_promotion")
            _check(any(e.get("endpoint") == backup.endpoint
                       for e in promos),
                   f"[{mode}] surviving backup's flight recorder shows "
                   f"the promotion event")
            fo = obs_metrics.default_registry().get(
                "pserver_client_primary_failovers_total")
            print(f"  [{mode}] client primary failovers: "
                  f"{fo.total() if fo else 0:.0f}")
            tr.close()
        finally:
            fluid.set_flag("observe", False)
            primary.stop()
            backup.stop()


def drill_ps_partition(seed, workdir, trace_out=None):
    """fluid-quorum: ASYMMETRIC network partition of a quorum-armed
    haven pair, under async AND sync PS.

    The partition isolates the primary from its backup AND from a
    majority of the 3 arbiters (it keeps exactly one — the minority
    side), while the backup reaches the majority and the trainer
    reaches everyone — the scenario the crash-stop model could not
    survive. PASS requires, per PS mode:

      * at most ONE write-acceptor at every 5ms-grain sample across the
        whole drill (the fenced minority primary holds, never acks);
      * the majority side promotes within the lease budget and the
        minority primary steps down (its later epoch-stale write is
        REJECTED with a redirect, not applied);
      * zero trainer-visible step failures and a final loss inside the
        no-fault band;
      * healing rejoins the deposed node as a resyncing standby,
        bit-identical to the new primary, with zero lost acked updates
        (the backup's pre-partition ack watermark survives);
      * the promotion is metered (kind="quorum") and the grant /
        step-down evidence is in the metrics + flight recorder.
    """
    import threading

    from paddle_tpu.observe import flight as obs_flight
    from paddle_tpu.pserver import ParameterServer
    from paddle_tpu.quorum import QuorumNode

    LEASE = 1.0
    N_BASE = 14
    for mode in ("async", "sync"):
        sync = mode == "sync"
        fluid.set_flag("observe", True)
        obs_metrics.default_registry().reset()

        # no-fault baseline: the loss band reference
        solo = ParameterServer("127.0.0.1:0").start()
        try:
            tr, loss, batch = _build_world(solo.endpoint, seed, sync=sync)
            ref = _run_steps(tr, loss, batch, N_BASE)
            tr.close()
        finally:
            solo.stop()

        qdir = os.path.join(workdir, f"quorum_{mode}")
        nodes, servers = [], []
        net, tr = None, None
        stop = threading.Event()
        try:
            # everything that can fail to start lives INSIDE the try:
            # a raised start (e.g. a lost bootstrap election) must not
            # leak arbiter threads/servers into the rest of the CI run
            nodes = [QuorumNode("127.0.0.1:0", qdir,
                                node_id=f"n{i}").start()
                     for i in range(3)]
            qeps = [n.endpoint for n in nodes]
            backup = ParameterServer("127.0.0.1:0").start()
            servers.append(backup)
            backup.start_standby(lease_s=LEASE, quorum_endpoints=qeps,
                                 quorum_resource="shard0")
            primary = ParameterServer("127.0.0.1:0").start()
            servers.append(primary)
            primary.start_replication(backup.endpoint, lease_s=LEASE,
                                      quorum_endpoints=qeps,
                                      quorum_resource="shard0")
            servers = [primary, backup]
            tr, loss, batch = _build_world(
                primary.endpoint, seed, sync=sync,
                haven_replicas={primary.endpoint: [backup.endpoint]},
                quorum_endpoints=qeps,
                quorum_resources={primary.endpoint: "shard0"})
            losses, failures = [], []

            def train_loop():
                while not stop.is_set():
                    try:
                        l, = tr.step(batch(), fetch_list=[loss])
                        losses.append(float(np.asarray(l).reshape(-1)[0]))
                    except Exception as e:          # noqa: BLE001
                        failures.append(repr(e))

            # 5ms write-acceptance sampler over BOTH members: fenced or
            # held primaries report accepting=False, so the invariant
            # is at most one True at every sample
            violations = []

            def sample_acceptors():
                while not stop.is_set():
                    acc = [s._haven.status()["accepting"] for s in servers]
                    if sum(acc) > 1:
                        violations.append(list(acc))
                    time.sleep(0.005)

            # flight-ring collector: the bounded ring holds <1s of
            # history at this step rate, so the promotion/step-down
            # evidence is harvested continuously instead of at the end
            seen_events = {"haven_promotion": [], "haven_step_down": []}

            def collect_flight():
                while not stop.is_set():
                    for k, acc_l in seen_events.items():
                        for e in obs_flight.get_flight().events(k):
                            if e not in acc_l:
                                acc_l.append(e)
                    time.sleep(0.05)

            t_train = threading.Thread(target=train_loop, daemon=True)
            t_samp = threading.Thread(target=sample_acceptors, daemon=True)
            t_coll = threading.Thread(target=collect_flight, daemon=True)
            t_train.start()
            t_samp.start()
            t_coll.start()
            time.sleep(1.2)
            pre_steps = len(losses)
            _check(pre_steps > 0, f"[{mode}] healthy steps before the "
                                  f"partition ({pre_steps})")
            pre_acked = primary._haven.log.acked_seq

            # the asymmetric cut: pair severed; primary keeps ONE
            # arbiter (minority), backup keeps all three (majority);
            # the trainer reaches everyone
            net = chaos.NetPartition(seed=seed).start()
            net.isolate(primary.endpoint, backup.endpoint)
            net.block(primary.endpoint, qeps[1])
            net.block(primary.endpoint, qeps[2])
            print(f"  [{mode}] partition up: primary sees 1/3 arbiters, "
                  f"backup sees 3/3, pair severed")

            budget_s = LEASE + LEASE / 3.0 + 2.0   # expiry + poll + grants
            t0 = time.monotonic()
            while backup._haven.role != "primary":
                if time.monotonic() - t0 > budget_s + 5.0:
                    raise DrillFailure(
                        f"[{mode}] backup never promoted "
                        f"(backup={backup._haven.status()})")
                time.sleep(0.01)
            took = time.monotonic() - t0
            _check(took <= budget_s + 2.0,
                   f"[{mode}] majority-side promotion in {took:.2f}s "
                   f"(lease budget ~{budget_s:.1f}s)")
            t0 = time.monotonic()
            while primary._haven.role == "primary":
                if time.monotonic() - t0 > budget_s + 5.0:
                    raise DrillFailure(f"[{mode}] minority primary never "
                                       f"stepped down")
                time.sleep(0.01)
            _check(primary._haven.role == "backup"
                   and not primary._haven.has_synced,
                   f"[{mode}] minority primary stepped down to an "
                   f"UNSYNCED standby")

            # epoch-stale write at the deposed node: REJECTED (redirect
            # verdict — the node no longer rules), never applied. The
            # raw client has no replica/quorum route on purpose: it
            # models a stale trainer still holding the old primary's
            # socket.
            w_before = {n: v.copy() for n, v in primary._dense.items()}
            raw = PSClient([primary.endpoint], failover_s=1.0)
            name = sorted(w_before)[0]
            rejected = False
            try:
                raw._call(primary.endpoint, "push_grad", name=name,
                          grad=np.ones_like(w_before[name]))
            except RuntimeError as e:
                rejected = "NotPrimary" in str(e) or "redirect" in str(e)
                print(f"  [{mode}] stale write rejected: {str(e)[:80]}")
            raw.close()
            _check(rejected, f"[{mode}] deposed node answered the stale "
                             f"write with a rejection")
            _check(all(np.array_equal(primary._dense[n], w_before[n])
                       for n in w_before),
                   f"[{mode}] deposed node applied NOTHING after the "
                   f"step-down (epoch-stale writes rejected)")

            # zero lost acked updates: the promoted backup's replay
            # watermark covers everything it had acknowledged
            _check(backup._haven.applied_seq >= pre_acked,
                   f"[{mode}] acked prefix survives "
                   f"({backup._haven.applied_seq} >= {pre_acked})")


            time.sleep(1.0)   # traffic against the new primary
            # heal: the deposed node rejoins as a resyncing standby
            net.heal()
            print(f"  [{mode}] partition healed")
            t0 = time.monotonic()
            while not primary._haven.has_synced:
                if time.monotonic() - t0 > 20.0:
                    raise DrillFailure(f"[{mode}] healed node never "
                                       f"resynced")
                time.sleep(0.02)
            time.sleep(0.6)
            stop.set()
            t_train.join(timeout=30)
            t_samp.join(timeout=5)

            _check(not failures,
                   f"[{mode}] zero trainer-visible failures "
                   f"({len(losses)} steps; first: "
                   f"{failures[0] if failures else None})")
            _check(len(losses) > pre_steps,
                   f"[{mode}] training continued through the partition "
                   f"({len(losses) - pre_steps} post-cut steps)")
            _check(not violations,
                   f"[{mode}] at most one write-acceptor at every 5ms "
                   f"sample ({violations[:3] if violations else 'clean'})")
            _check(np.isfinite(losses).all(), f"[{mode}] all losses finite")
            band = np.mean(ref[-6:]) * 1.25 + 0.05
            _check(np.mean(losses[-6:]) < band,
                   f"[{mode}] final loss {np.mean(losses[-6:]):.4f} "
                   f"inside the no-fault band (<{band:.4f})")

            # healed standby is bit-identical to the new primary at the
            # drained watermark
            deadline = time.monotonic() + 10.0
            while backup._haven.log.lag() > 0:
                if time.monotonic() > deadline:
                    raise DrillFailure(f"[{mode}] resync never drained")
                time.sleep(0.02)
            _check(all(np.array_equal(primary._dense[n],
                                      backup._dense[n])
                       for n in backup._dense),
                   f"[{mode}] healed standby bit-identical to the new "
                   f"primary")

            reg = obs_metrics.default_registry()
            promoted = reg.get("ps_promotions_total")
            _check(promoted is not None
                   and promoted.value(kind="quorum") >= 1,
                   f"[{mode}] quorum promotion metered")
            stepdowns = reg.get("ps_step_downs_total")
            _check(stepdowns is not None and stepdowns.total() >= 1,
                   f"[{mode}] step-down metered")
            grants = reg.get("quorum_grants_total")
            _check(grants is not None
                   and grants.value(outcome="granted") >= 2,
                   f"[{mode}] grants metered "
                   f"(bootstrap + election)")
            epoch_g = reg.get("quorum_lease_epoch")
            _check(epoch_g is not None
                   and epoch_g.value(resource="shard0") >= 2,
                   f"[{mode}] quorum_lease_epoch gauge advanced")
            _check(any(e.get("endpoint") == backup.endpoint
                       and e.get("promotion") == "quorum"
                       for e in seen_events["haven_promotion"]),
                   f"[{mode}] promotion in the flight recorder")
            _check(any(e.get("endpoint") == primary.endpoint
                       for e in seen_events["haven_step_down"]),
                   f"[{mode}] step-down in the flight recorder")
        finally:
            stop.set()
            if net is not None:
                net.stop()
            if tr is not None:
                try:
                    tr.close()
                except Exception:   # noqa: BLE001
                    pass
            fluid.set_flag("observe", False)
            for s in servers:
                s.stop()
            for n in nodes:
                n.stop()


def drill_ps_handover(seed, workdir, trace_out=None):
    """fluid-haven: planned live shard handoff under continuous async
    training load (see module docstring)."""
    import threading

    from paddle_tpu.pserver import ParameterServer

    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    primary, backup = _haven_pair(lease_s=1.0)
    fresh = ParameterServer("127.0.0.1:0").start()
    fresh.start_standby(lease_s=1.0, auto_promote=False)
    servers = [primary, backup, fresh]
    try:
        tr, loss, batch = _build_world(
            primary.endpoint, seed,
            haven_replicas={primary.endpoint: [backup.endpoint,
                                               fresh.endpoint]})
        stop = threading.Event()
        losses, failures = [], []

        def train_loop():
            while not stop.is_set():
                try:
                    l, = tr.step(batch(), fetch_list=[loss])
                    losses.append(float(np.asarray(l).reshape(-1)[0]))
                except Exception as e:          # noqa: BLE001
                    failures.append(repr(e))

        # lease-holder sampler: at EVERY sampled instant at most one of
        # the three servers may be ACCEPTING writes. (`accepting`, not
        # bare role: during the promote RPC round-trip the predecessor
        # still carries the primary role but its mutator gate is held —
        # it cannot acknowledge a write, so the successor is the sole
        # lease-holder the moment it processes the promote.)
        violations = []

        def sample_roles():
            while not stop.is_set():
                acc = [s._haven.status()["accepting"] if s._haven
                       else True for s in servers]
                if sum(acc) > 1:
                    violations.append(list(acc))
                time.sleep(0.005)

        t_train = threading.Thread(target=train_loop, daemon=True)
        t_roles = threading.Thread(target=sample_roles, daemon=True)
        t_train.start()
        t_roles.start()
        time.sleep(1.0)
        pre_steps = len(losses)
        res = primary.handover(fresh.endpoint)
        print(f"  handover complete: successor {res['successor']} at "
              f"epoch {res['epoch']}, seq {res['seq']}")
        time.sleep(1.5)
        stop.set()
        t_train.join(timeout=30)
        t_roles.join(timeout=5)
        _check(not failures,
               f"zero failed trainer steps across the handoff "
               f"({len(losses)} steps; first failure: "
               f"{failures[0] if failures else None})")
        _check(len(losses) > pre_steps,
               f"training continued against the successor "
               f"({len(losses) - pre_steps} post-flip steps)")
        _check(not violations,
               f"exactly one lease-holder at every sampled instant "
               f"({violations[:3] if violations else 'clean'})")
        _check(fresh._haven.role == "primary"
               and primary._haven.role == "retired",
               "roles flipped: successor primary, predecessor retired")
        promoted = obs_metrics.default_registry().get(
            "ps_promotions_total")
        _check(promoted is not None
               and promoted.value(kind="handover") >= 1,
               "handover promotion metered")
        _check(np.isfinite(losses).all(), "all losses finite")
        tr.close()
    finally:
        fluid.set_flag("observe", False)
        for s in servers:
            s.stop()


# -- fluid-elastic: HA data plane -----------------------------------------

def _master_ha_world(workdir, lease_s=0.5, timeout_dur=5.0):
    """3 arbiters + primary/standby master pair (quorum-fenced)."""
    from paddle_tpu.master import Master
    from paddle_tpu.quorum import QuorumNode

    qdir = os.path.join(workdir, "mq")
    nodes = [QuorumNode("127.0.0.1:0", qdir, node_id=f"mn{i}").start()
             for i in range(3)]
    qeps = [n.endpoint for n in nodes]
    standby = Master("127.0.0.1:0",
                     snapshot_path=os.path.join(workdir, "standby.json"),
                     timeout_dur=timeout_dur, check_interval=0.1).start()
    standby.start_standby(lease_s=lease_s, quorum_endpoints=qeps,
                          quorum_resource="master0")
    primary = Master("127.0.0.1:0",
                     snapshot_path=os.path.join(workdir, "primary.json"),
                     timeout_dur=timeout_dur, check_interval=0.1).start()
    primary.start_replication(standby.endpoint, lease_s=lease_s,
                              quorum_endpoints=qeps,
                              quorum_resource="master0")
    return nodes, qeps, primary, standby


def _run_master_consumers(primary, standby, qeps, n_consumers=2,
                          item_sleep=0.02):
    """Consumer threads streaming master-leased records; returns the
    shared bookkeeping the checks read. Each delivered payload item and
    each successful RPC timestamp is recorded — the blip measurement."""
    import threading

    from paddle_tpu.master import MasterClient

    lock = threading.Lock()
    state = {"deliveries": [], "failures": [], "op_times": [],
             "threads": [], "lock": lock}

    def consumer(cid):
        mc = MasterClient(primary.endpoint, standbys=[standby.endpoint],
                          quorum_endpoints=qeps, quorum_resource="master0",
                          failover_s=20.0)
        try:
            while True:
                status, task = mc.get_task()
                with lock:
                    state["op_times"].append(time.monotonic())
                if status == "no_more":
                    return
                if status == "none":
                    time.sleep(0.05)
                    continue
                for item in task["payload"]:
                    time.sleep(item_sleep)       # "process" the record
                    with lock:
                        state["deliveries"].append(item)
                mc.task_finished(task["task_id"], task["epoch"])
                with lock:
                    state["op_times"].append(time.monotonic())
        except Exception as e:                   # noqa: BLE001
            with lock:
                state["failures"].append((cid, repr(e)))
        finally:
            mc.close()

    for cid in range(n_consumers):
        th = threading.Thread(target=consumer, args=(cid,), daemon=True)
        state["threads"].append(th)
        th.start()
    return state


def _check_master_exactly_once(ruler, deliveries, n_items):
    """Exactly-once accounting: every payload item delivered >= 1, and
    an item is delivered MORE than once only when its task was
    re-issued (task epoch > 1 — the documented failure-budget path)."""
    from collections import Counter

    counts = Counter(deliveries)
    missing = [i for i in range(n_items) if counts[i] == 0]
    _check(not missing, f"every record delivered ({len(missing)} missing)")
    reissued = 0
    with ruler._lock:
        done = list(ruler._done)
    dup_violations = []
    for t in done:
        if t.epoch > 1:
            reissued += 1
            continue
        for item in t.payload:
            if counts[item] != 1:
                dup_violations.append((item, counts[item]))
    _check(not dup_violations,
           f"single-issue tasks delivered EXACTLY once "
           f"({dup_violations[:3] if dup_violations else 'clean'}; "
           f"{reissued} re-issued tasks allowed duplicates)")


def drill_master_kill(seed, workdir, trace_out=None):
    """fluid-elastic: SIGKILL the primary data master mid-pass (see
    module docstring)."""
    import threading

    from paddle_tpu.observe import flight as obs_flight

    LEASE = 0.5
    N_ITEMS, CHUNK = 60, 2                      # 30 tasks
    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    from paddle_tpu.master import MasterClient
    nodes, qeps, primary, standby = _master_ha_world(workdir,
                                                     lease_s=LEASE)
    stop_sampling = threading.Event()
    state = None
    try:
        admin = MasterClient(primary.endpoint)
        admin.set_dataset(list(range(N_ITEMS)), chunks_per_task=CHUNK)
        admin.close()

        violations = []

        def sample_issuing():
            while not stop_sampling.is_set():
                acc = [primary.issuing, standby.issuing]
                if sum(acc) > 1:
                    violations.append(list(acc))
                time.sleep(0.005)

        threading.Thread(target=sample_issuing, daemon=True).start()
        state = _run_master_consumers(primary, standby, qeps)

        # let roughly a third of the pass complete at the primary
        deadline = time.monotonic() + 30
        while True:
            with primary._lock:
                done = len(primary._done)
            if done >= 10:
                break
            if time.monotonic() > deadline:
                raise DrillFailure("pass made no progress at the primary")
            time.sleep(0.02)

        kill_at = time.monotonic()
        chaos.kill_master(primary)
        print(f"  SIGKILL'd primary master {primary.endpoint} "
              f"({done} tasks done)")
        budget_s = LEASE + LEASE / 3.0 + 2.0    # expiry + poll + grants
        while standby.ha_status()["role"] != "primary":
            if time.monotonic() - kill_at > budget_s + 5.0:
                raise DrillFailure(
                    f"standby never promoted ({standby.ha_status()})")
            time.sleep(0.01)
        took = time.monotonic() - kill_at
        _check(took <= budget_s,
               f"standby promoted in {took:.2f}s (lease budget "
               f"~{budget_s:.1f}s)")

        for th in state["threads"]:
            th.join(timeout=60)
        _check(all(not th.is_alive() for th in state["threads"]),
               "both consumers drained the pass")
        stop_sampling.set()
        _check(not state["failures"],
               f"zero consumer-visible failures "
               f"({state['failures'][:2] if state['failures'] else 'clean'})")
        # the stall is bounded by the blip: the largest gap between
        # consecutive successful ops must not exceed the failover budget
        ops = sorted(state["op_times"])
        gaps = [b - a for a, b in zip(ops, ops[1:])]
        blip = max(gaps) if gaps else 0.0
        _check(blip <= budget_s + 2.0,
               f"max consumer stall {blip:.2f}s bounded by the failover "
               f"blip (budget ~{budget_s:.1f}s)")
        _check(not violations,
               f"at most one task-issuing master at every 5ms sample")
        st = standby.ha_status()
        _check(st["done"] == N_ITEMS // CHUNK and st["todo"] == 0
               and st["pending"] == 0,
               f"pass complete at the promoted master ({st})")
        _check_master_exactly_once(standby, state["deliveries"], N_ITEMS)
        promoted = obs_metrics.default_registry().get(
            "master_promotions_total")
        _check(promoted is not None
               and promoted.value(kind="quorum") >= 1,
               "quorum promotion metered")
        promos = obs_flight.get_flight().events("master_promotion")
        _check(any(e.get("endpoint") == standby.endpoint for e in promos),
               "promotion in the flight recorder")
    finally:
        stop_sampling.set()
        fluid.set_flag("observe", False)
        primary.stop()
        standby.stop()
        for n in nodes:
            n.stop()


def drill_master_partition(seed, workdir, trace_out=None):
    """fluid-elastic: asymmetric partition of the master pair — the
    minority primary fences, trainers follow the quorum holder (see
    module docstring)."""
    import threading

    from paddle_tpu.ark.retry import NO_RETRY

    LEASE = 0.5
    N_ITEMS, CHUNK = 60, 2
    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    from paddle_tpu.master import MasterClient
    nodes, qeps, primary, standby = _master_ha_world(workdir,
                                                     lease_s=LEASE)
    stop_sampling = threading.Event()
    net, state = None, None
    try:
        admin = MasterClient(primary.endpoint)
        admin.set_dataset(list(range(N_ITEMS)), chunks_per_task=CHUNK)
        admin.close()

        violations = []

        def sample_issuing():
            while not stop_sampling.is_set():
                acc = [primary.issuing, standby.issuing]
                if sum(acc) > 1:
                    violations.append(list(acc))
                time.sleep(0.005)

        threading.Thread(target=sample_issuing, daemon=True).start()
        state = _run_master_consumers(primary, standby, qeps)

        deadline = time.monotonic() + 30
        while True:
            with primary._lock:
                done = len(primary._done)
            if done >= 8:
                break
            if time.monotonic() > deadline:
                raise DrillFailure("pass made no progress at the primary")
            time.sleep(0.02)

        # the asymmetric cut: pair severed; primary keeps ONE arbiter
        # (minority), standby keeps all three; consumers reach everyone
        net = chaos.NetPartition(seed=seed).start()
        net.isolate(primary.endpoint, standby.endpoint)
        net.block(primary.endpoint, qeps[1])
        net.block(primary.endpoint, qeps[2])
        cut_at = time.monotonic()
        print(f"  partition up: primary sees 1/3 arbiters, standby 3/3, "
              f"pair severed ({done} tasks done)")
        budget_s = LEASE + LEASE / 3.0 + 2.0
        while standby.ha_status()["role"] != "primary":
            if time.monotonic() - cut_at > budget_s + 5.0:
                raise DrillFailure(
                    f"majority-side standby never promoted "
                    f"({standby.ha_status()})")
            time.sleep(0.01)
        took = time.monotonic() - cut_at
        _check(took <= budget_s,
               f"majority-side promotion in {took:.2f}s (budget "
               f"~{budget_s:.1f}s)")
        t0 = time.monotonic()
        while primary.issuing:
            if time.monotonic() - t0 > budget_s + 5.0:
                raise DrillFailure("minority primary never fenced")
            time.sleep(0.01)
        print(f"  minority primary fenced/stepped down "
              f"(role {primary.ha_status()['role']})")

        # a stale client still holding the deposed primary must get a
        # rejection (redirect -> NotMaster), never a state mutation
        raw = MasterClient(primary.endpoint, retry=NO_RETRY,
                           failover_s=0.5)
        rejected = False
        try:
            raw.get_task()
        except (RuntimeError, ConnectionError, OSError) as e:
            rejected = "NotMaster" in str(e) or "redirect" in str(e) \
                or isinstance(e, (ConnectionError, OSError))
            print(f"  stale get_task at the deposed primary rejected: "
                  f"{str(e)[:80]}")
        raw.close()
        _check(rejected, "deposed primary rejects task commands")

        for th in state["threads"]:
            th.join(timeout=60)
        _check(all(not th.is_alive() for th in state["threads"]),
               "consumers drained the pass following the quorum holder")
        stop_sampling.set()
        net.heal()
        _check(not state["failures"],
               f"zero consumer-visible failures "
               f"({state['failures'][:2] if state['failures'] else 'clean'})")
        _check(not violations,
               "at most one task-issuing master at every 5ms sample")
        st = standby.ha_status()
        _check(st["done"] == N_ITEMS // CHUNK and st["todo"] == 0
               and st["pending"] == 0,
               f"pass complete at the promoted master ({st})")
        _check_master_exactly_once(standby, state["deliveries"], N_ITEMS)
        reg = obs_metrics.default_registry()
        promoted = reg.get("master_promotions_total")
        _check(promoted is not None
               and promoted.value(kind="quorum") >= 1,
               "quorum promotion metered")
        stepdowns = reg.get("master_step_downs_total")
        _check(stepdowns is not None and stepdowns.total() >= 1,
               "minority step-down metered")
    finally:
        stop_sampling.set()
        if net is not None:
            net.stop()
        fluid.set_flag("observe", False)
        primary.stop()
        standby.stop()
        for n in nodes:
            n.stop()


def _build_sync_member(eps, seed, trainer_id, trainers, lease_s,
                       lr=0.1):
    """One sync-PS trainer world (own program/scope/executor) with its
    step PRE-COMPILED outside the barrier loop (two concurrent first
    compiles on a contended box can outlast the barrier)."""
    from paddle_tpu.pserver import SyncPSTrainer

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="int64")
        h = layers.fc(input=x, size=16, act="relu")
        logits = layers.fc(input=h, size=2, act=None)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    main.random_seed = startup.random_seed = seed
    cfg = fluid.DistributeTranspilerConfig()
    cfg.runtime = "pserver"
    t = fluid.DistributeTranspiler(cfg)
    t.transpile(trainer_id=trainer_id, program=main, pservers=eps,
                trainers=trainers, sync_mode=True)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    # pre-compile with the exact (feed, fetch) signature tr.step uses
    grad_fetches = [t.grad_names[p] for p in t.param_specs]
    rng = np.random.RandomState(0)
    exe.run(main, feed={"x": rng.randn(4, 8).astype(np.float32),
                        "y": np.zeros((4, 1), np.int64)},
            fetch_list=[loss] + grad_fetches, scope=scope)
    tr = SyncPSTrainer(t, exe, program=main, scope=scope,
                       heartbeat_lease_s=lease_s)
    tr.init_params()               # first writer wins
    return tr, loss


def drill_trainer_churn(seed, workdir, trace_out=None):
    """fluid-elastic scale-down AND scale-up: kill 1-of-3 sync trainers
    mid-pass, start a replacement with a FRESH trainer id (see module
    docstring)."""
    import threading

    from paddle_tpu.master import Master, MasterClient
    from paddle_tpu.pserver import ParameterServer

    N_BATCH = 60
    LEASE = 0.5
    RECORD_S = 0.05   # per-record pacing: the pass must outlive the
    #                   churn window so the replacement gets real work

    def batch_of(i, n=32):
        rng = np.random.RandomState(seed * 1000 + i)
        w_true = np.random.RandomState(seed + 1).randn(8, 2)
        xs = rng.randn(n, 8).astype(np.float32)
        ys = (xs @ w_true).argmax(1).astype(np.int64).reshape(n, 1)
        return {"x": xs, "y": ys}

    def run(churn):
        srv = ParameterServer("127.0.0.1:0", trainers=3).start()
        master = Master("127.0.0.1:0", timeout_dur=4.0,
                        check_interval=0.1).start()
        admin = MasterClient(master.endpoint)
        admin.set_dataset(list(range(N_BATCH)))
        lock = threading.Lock()
        deliveries, losses, failures = [], [], []
        kill_evt = threading.Event()
        stop_sampling = threading.Event()
        world_sizes = []

        def sample_world():
            while not stop_sampling.is_set():
                w = srv._sync_barrier.live_parties
                if not world_sizes or world_sizes[-1] != w:
                    world_sizes.append(w)
                time.sleep(0.01)

        def consumer(tid, tr, loss, die=False):
            mc = MasterClient(master.endpoint)
            killed = False
            try:
                while True:
                    if die and kill_evt.is_set():
                        killed = True
                        return
                    status, task = mc.get_task()
                    if status == "no_more":
                        return
                    if status == "none":
                        time.sleep(0.05)
                        continue
                    for i in task["payload"]:
                        if die and kill_evt.is_set():
                            killed = True
                            return   # dies HOLDING the lease
                        l, = tr.step(batch_of(i), fetch_list=[loss])
                        time.sleep(RECORD_S)
                        with lock:
                            deliveries.append((tid, i))
                            losses.append(
                                float(np.asarray(l).reshape(-1)[0]))
                    mc.task_finished(task["task_id"], task["epoch"])
            except Exception as e:               # noqa: BLE001
                with lock:
                    failures.append((tid, repr(e)))
            finally:
                if killed:
                    # SIGKILL analog: the heartbeat dies with the
                    # process — no clean close, the lease just expires
                    tr._heartbeat.stop()
                    tr._hb_client.close()
                else:
                    tr.close()
                mc.close()

        threads = []
        try:
            # builds are SEQUENTIAL (program construction shares the
            # global unique-name state); only the loops run concurrently
            members = [( tid, *_build_sync_member(
                srv.endpoint, seed, tid, trainers=3, lease_s=LEASE))
                for tid in range(3)]
            threading.Thread(target=sample_world, daemon=True).start()
            for tid, tr, loss in members:
                th = threading.Thread(
                    target=consumer, args=(tid, tr, loss),
                    kwargs={"die": churn and tid == 1}, daemon=True)
                threads.append(th)
                th.start()
            if churn:
                # let the pass get going, then SIGKILL trainer 1
                deadline = time.monotonic() + 60
                while True:
                    with lock:
                        n = len(deliveries)
                    if n >= 5:
                        break
                    if time.monotonic() > deadline:
                        raise DrillFailure("pass never got going")
                    time.sleep(0.02)
                kill_evt.set()
                print(f"  killed trainer 1 mid-pass ({n} records in)")
                # world must degrade to 2 in lease-time
                t0 = time.monotonic()
                while srv._sync_barrier.live_parties > 2:
                    if time.monotonic() - t0 > 30:
                        raise DrillFailure("dead trainer never evicted")
                    time.sleep(0.02)
                print(f"  world degraded to 2 in "
                      f"{time.monotonic() - t0:.2f}s")
                # REPLACEMENT with a FRESH id, mid-job (build in the
                # main thread — construction is not thread-safe)
                t_adm = time.monotonic()
                _, tr3, loss3 = (3, *_build_sync_member(
                    srv.endpoint, seed, 3, trainers=3, lease_s=LEASE))
                th = threading.Thread(target=consumer,
                                      args=(3, tr3, loss3), daemon=True)
                threads.append(th)
                th.start()
                t0 = time.monotonic()
                while srv._sync_barrier.live_parties < 3:
                    if time.monotonic() - t0 > 30:
                        raise DrillFailure("replacement never admitted")
                    time.sleep(0.02)
                print(f"  replacement (id 3) admitted in "
                      f"{time.monotonic() - t_adm:.2f}s — world back to 3")
            for th in threads:
                th.join(timeout=300)
            if any(th.is_alive() for th in threads):
                raise DrillFailure("a trainer never drained the pass")
            stop_sampling.set()
            st = admin.stats()
            return {"deliveries": list(deliveries),
                    "losses": list(losses), "failures": list(failures),
                    "world_sizes": list(world_sizes), "stats": st,
                    "master": master}
        finally:
            stop_sampling.set()
            kill_evt.set()
            admin.close()
            srv.stop()
            if not churn:
                master.stop()

    fluid.set_flag("observe", True)
    obs_metrics.default_registry().reset()
    try:
        ref = run(churn=False)
        _check(not ref["failures"], "no-fault reference run clean")
        band = np.mean(ref["losses"][-6:]) * 1.3 + 0.05

        obs_metrics.default_registry().reset()
        got = run(churn=True)
        master = got["master"]
        try:
            _check(not got["failures"],
                   f"zero trainer-visible failures "
                   f"({got['failures'][:2] if got['failures'] else 'clean'})")
            # world size observed 3 -> 2 -> 3
            w = got["world_sizes"]
            sub, it = [3, 2, 3], iter(w)
            _check(all(any(x == want for x in it) for want in sub),
                   f"world size observed 3->2->3 (samples {w})")
            st = got["stats"]
            _check(st["done"] == N_BATCH and st["todo"] == 0
                   and st["pending"] == 0,
                   f"pass complete ({st})")
            by_replacement = sum(1 for tid, _i in got["deliveries"]
                                 if tid == 3)
            _check(by_replacement >= 1,
                   f"replacement trainer processed real work "
                   f"({by_replacement} records)")
            _check_master_exactly_once(
                master, [i for _tid, i in got["deliveries"]], N_BATCH)
            _check(np.isfinite(got["losses"]).all(), "all losses finite")
            tail = np.mean(got["losses"][-6:])
            _check(tail < band,
                   f"final loss {tail:.4f} inside the no-fault band "
                   f"(<{band:.4f})")
            reg = obs_metrics.default_registry()
            evicted = reg.get("pserver_trainers_evicted_total")
            _check(evicted is not None and evicted.total() >= 1,
                   "eviction metered")
            admitted = reg.get("pserver_trainers_admitted_total")
            _check(admitted is not None and admitted.total() >= 1,
                   "scale-up admission metered")
        finally:
            master.stop()
    finally:
        fluid.set_flag("observe", False)


SCENARIOS = {
    "flaky_rpc": drill_flaky_rpc,
    "master_kill": drill_master_kill,
    "master_partition": drill_master_partition,
    "trainer_churn": drill_trainer_churn,
    "ps_primary_kill": drill_ps_primary_kill,
    "ps_handover": drill_ps_handover,
    "ps_partition": drill_ps_partition,
    "replica_kill": drill_replica_kill,
    "decode_kill": drill_decode_kill,
    "quant_flaky_rpc": drill_quant_flaky_rpc,
    "pserver_kill": drill_pserver_kill,
    "ckpt_crash": drill_ckpt_crash,
    "sync_evict": drill_sync_evict,
    "dist_trace": drill_dist_trace,
    "health_alerts": drill_health_alerts,
}


def _export_and_merge(trace_out):
    """Write THIS process's trace file into `trace_out`, merge every
    per-process trace file found there, and fail unless every span
    survived the merge."""
    import glob
    import json

    from paddle_tpu.observe import get_tracer, merge_chrome_traces, xray

    if xray.process_name().startswith("pid"):
        xray.set_process_name("trainer0")
    mine = os.path.join(trace_out, f"trace_{xray.process_name()}.json")
    get_tracer().export_chrome(mine)
    inputs = sorted(glob.glob(os.path.join(trace_out, "trace_*.json")))
    merged_path = os.path.join(trace_out, "merged_trace.json")
    doc, stats = merge_chrome_traces(inputs, out_path=merged_path)
    with open(merged_path) as f:
        json.load(f)   # the artifact must round-trip
    _check(stats["spans_out"] == stats["spans_in"] and stats["spans_in"] > 0,
           f"merged {stats['spans_in']} spans from {len(inputs)} "
           f"process file(s), none dropped")
    print(f"  merged timeline: {merged_path} "
          f"(processes: {', '.join(stats['processes'])})")
    return merged_path, stats


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--scenario", required=True, choices=sorted(SCENARIOS),
                    help="fault scenario to drill")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    ap.add_argument("--trace-out", default=None, metavar="DIR",
                    help="write per-process chrome trace files + a merged "
                         "timeline here; the drill fails if the merge "
                         "drops spans")
    args = ap.parse_args()
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_drill_")
    os.makedirs(workdir, exist_ok=True)
    print(f"chaos drill: {args.scenario} (seed {args.seed})")
    t0 = time.monotonic()
    try:
        if args.trace_out:
            # root span around the whole scenario: the timeline shows
            # the drill's extent, and scenarios that make no RPC/executor
            # calls (ckpt_crash) still contribute >= 1 span to the merge
            from paddle_tpu.observe import xray
            with xray.span(f"drill:{args.scenario}", cat="drill",
                           seed=args.seed):
                SCENARIOS[args.scenario](args.seed, workdir,
                                         trace_out=args.trace_out)
            os.makedirs(args.trace_out, exist_ok=True)
            _export_and_merge(args.trace_out)
        else:
            SCENARIOS[args.scenario](args.seed, workdir,
                                     trace_out=args.trace_out)
    except DrillFailure as e:
        print(f"DRILL FAILED: {e}")
        return 1
    print(f"DRILL PASSED in {time.monotonic() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
