#!/usr/bin/env python
"""elastic_bench: fluid-elastic HA data-plane numbers (host TCP + json,
no device work — bench.py runs this as a CPU subprocess segment).

Two measurements, printed as one JSON line:

- ``master_failover_blip_ms``: a consumer streams get_task/finish
  against a quorum-armed primary/standby master pair; the primary is
  SIGKILL-equivalently cut mid-stream and the blip is the largest gap
  between consecutive successful consumer ops across the kill — lease
  expiry + election + client re-resolution, end to end. Gated against
  ``master_failover_budget_ms`` (two lease periods + a retry/resolve
  allowance, the same shape as the quorum/haven failover budgets).

- ``elastic_scaleup_admission_s``: a running 2-trainer sync-PS world
  (client-level lockstep, the sync_evict drill idiom) admits a THIRD,
  never-seen trainer id; the admission time runs from its first
  heartbeat to the first barrier generation whose world counts it
  (live_parties == 3) — the scale-UP half of elasticity. Gated at the
  barrier-epoch bound: admission must land within one generation plus
  a lease period (``elastic_scaleup_ok``).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def bench_master_failover(workdir, lease_s=0.5, n_items=200):
    from paddle_tpu.ark import chaos
    from paddle_tpu.master import Master, MasterClient
    from paddle_tpu.quorum import QuorumNode

    nodes = [QuorumNode("127.0.0.1:0", os.path.join(workdir, "q"),
                        node_id=f"b{i}").start() for i in range(3)]
    qeps = [n.endpoint for n in nodes]
    standby = Master("127.0.0.1:0").start()
    standby.start_standby(lease_s=lease_s, quorum_endpoints=qeps,
                          quorum_resource="bench")
    primary = Master("127.0.0.1:0", timeout_dur=10.0,
                     check_interval=0.1).start()
    primary.start_replication(standby.endpoint, lease_s=lease_s,
                              quorum_endpoints=qeps,
                              quorum_resource="bench")
    cli = MasterClient(primary.endpoint, standbys=[standby.endpoint],
                       quorum_endpoints=qeps, quorum_resource="bench",
                       failover_s=20.0)
    try:
        cli.set_dataset(list(range(n_items)), chunks_per_task=1)
        op_times = []
        killed_at = None
        done = 0
        while True:
            status, task = cli.get_task()
            op_times.append(time.monotonic())
            if status == "no_more":
                break
            if status == "none":
                time.sleep(0.01)
                continue
            cli.task_finished(task["task_id"], task["epoch"])
            op_times.append(time.monotonic())
            done += 1
            if killed_at is None and done >= n_items // 3:
                killed_at = time.monotonic()
                chaos.kill_master(primary)
        gaps = [(b - a) for a, b in zip(op_times, op_times[1:])]
        blip_ms = max(gaps) * 1000.0 if gaps else 0.0
        # two lease periods (local expiry is conservative vs the
        # arbiters' own) + election + client resolve allowance
        budget_ms = (2.0 * lease_s + 2.0) * 1000.0
        return {"master_failover_blip_ms": round(blip_ms, 1),
                "master_failover_budget_ms": round(budget_ms, 1),
                "master_failover_ok": blip_ms <= budget_ms,
                "master_failover_tasks_done": done}
    finally:
        cli.close()
        primary.stop()
        standby.stop()
        for n in nodes:
            n.stop()


def bench_scaleup_admission(lease_s=0.5):
    from paddle_tpu.pserver import ParameterServer, PSClient

    srv = ParameterServer("127.0.0.1:0", trainers=2).start()
    ep = srv.endpoint
    stop = threading.Event()
    admitted = {}

    def trainer(tid, session, start_batch=0):
        c = PSClient([ep])
        c2 = None
        try:
            c.init_param(ep, "w", np.zeros(8, np.float32), "sgd", 0.1, {})
            c.heartbeat(ep, trainer_id=tid, session=session,
                        lease_s=lease_s)
            if tid == 2:
                admitted["beat_at"] = time.monotonic()
            hb_stop = threading.Event()

            def hb():
                while not hb_stop.wait(lease_s / 3.0):
                    try:
                        c2.heartbeat(ep, trainer_id=tid, session=session,
                                     lease_s=lease_s)
                    except Exception:   # noqa: BLE001
                        pass

            c2 = PSClient([ep])
            threading.Thread(target=hb, daemon=True).start()
            b = start_batch
            while not stop.is_set():     # steady state until measured
                try:
                    c.push_grads_sync(
                        {ep: {"w": np.full(8, 0.1, np.float32)}},
                        batch_id=b, trainer_id=tid, session=session)
                    c.sync_apply([ep], trainer_id=tid)
                except RuntimeError:
                    continue   # broken barrier around the churn: retry
                b += 1
                time.sleep(0.005)
            hb_stop.set()
        finally:
            c.close()
            if c2 is not None:
                c2.close()

    threads = [threading.Thread(target=trainer, args=(tid, f"s{tid}"),
                                daemon=True) for tid in range(2)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.5)            # the 2-world is in steady state
        t3 = threading.Thread(target=trainer, args=(2, "s2"),
                              kwargs={"start_batch": 0}, daemon=True)
        t3.start()
        threads.append(t3)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if "beat_at" in admitted \
                    and srv._sync_barrier.live_parties >= 3:
                admitted["admitted_at"] = time.monotonic()
                break
            time.sleep(0.005)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        if "admitted_at" not in admitted or "beat_at" not in admitted:
            return {"elastic_scaleup_admission_s": -1.0,
                    "elastic_scaleup_ok": False}
        adm = admitted["admitted_at"] - admitted["beat_at"]
        # bound: one in-flight generation (at most a few barrier polls)
        # plus one lease period of slack
        ok = adm <= lease_s + 2.0
        return {"elastic_scaleup_admission_s": round(adm, 3),
                "elastic_scaleup_ok": ok}
    finally:
        stop.set()
        srv.stop()


def main():
    workdir = tempfile.mkdtemp(prefix="elastic_bench_")
    rec = {}
    rec.update(bench_master_failover(workdir))
    rec.update(bench_scaleup_admission())
    print(json.dumps(rec))
    return 0 if (rec.get("master_failover_ok")
                 and rec.get("elastic_scaleup_ok")) else 1


if __name__ == "__main__":
    sys.exit(main())
