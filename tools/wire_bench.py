#!/usr/bin/env python
"""fluid-wire bench: bytes/step + step-time A/B for the quantized
parameter-server wire (raw vs `comm_quant`), printed as ONE JSON line.

Runs the process-based sync-PS dense push path (the RunSyncLoop analog:
push_grads_sync + sync_apply barrier every batch) twice from identical
seeded state — once with raw float32 payloads, once with the int8
per-chunk codec + client-side error feedback — and reads the wire byte
counters (`pserver_wire_bytes_raw` / `_encoded`) the client records per
command. A sparse leg measures the embedding-row pull/push compression
(the DeepFM millions-of-users shape).

Keys: wire_bytes_per_step_raw, wire_bytes_per_step_encoded,
wire_compression_x, wire_sync_ps_step_ms_raw, wire_sync_ps_step_ms_quant,
wire_sparse_compression_x, wire_quant_loss_delta (mean |loss_q - loss_raw|
over the run — the convergence-neutrality readout).

Loopback TCP is latency- not bandwidth-bound, so the step-time A/B here
mostly prices the codec's host cost; the bytes/step ratio is the
transferable result (a DCN/NIC-bound deployment converts bytes directly
into wall time). bench.py runs this in a CPU subprocess (`wire` segment).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

STEPS = 12
WARMUP = 2


def _build(fluid, layers, seed=11):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = layers.data(name="x", shape=[64], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="int64")
        h = layers.fc(input=x, size=256, act="relu")
        h = layers.fc(input=h, size=256, act="relu")
        logits = layers.fc(input=h, size=2, act=None)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    main.random_seed = startup.random_seed = seed
    return main, startup, loss


def run_sync_ps(fluid, layers, np, codec):
    """One sync-PS run; returns (per-step raw bytes, per-step encoded
    bytes, mean step ms, losses) for the push_grads_sync command."""
    from paddle_tpu import observe
    from paddle_tpu.pserver import ParameterServer, SyncPSTrainer
    from paddle_tpu.wire import ENCODED_BYTES_METRIC, RAW_BYTES_METRIC

    observe.reset_all()
    srv = ParameterServer("127.0.0.1:0", trainers=1).start()
    try:
        main, startup, loss = _build(fluid, layers)
        cfg = fluid.DistributeTranspilerConfig()
        cfg.runtime = "pserver"
        cfg.comm_quant = codec
        t = fluid.DistributeTranspiler(cfg)
        t.transpile(trainer_id=0, program=main, pservers=srv.endpoint,
                    trainers=1, sync_mode=True)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)
        tr = SyncPSTrainer(t, exe, scope=scope)
        tr.init_params()

        rng = np.random.RandomState(5)
        w_true = rng.randn(64, 2).astype(np.float32)

        def batch(n=64):
            xs = rng.randn(n, 64).astype(np.float32)
            ys = (xs @ w_true).argmax(1).astype(np.int64).reshape(n, 1)
            return {"x": xs, "y": ys}

        losses = []
        for _ in range(WARMUP):
            tr.step(batch(), fetch_list=[loss])
        reg = observe.default_registry()

        def _bytes():
            raw = reg.get(RAW_BYTES_METRIC)
            enc = reg.get(ENCODED_BYTES_METRIC)
            return (raw.value(cmd="push_grads_sync") if raw else 0.0,
                    enc.value(cmd="push_grads_sync") if enc else 0.0)

        raw0, enc0 = _bytes()
        t0 = time.perf_counter()
        for _ in range(STEPS):
            l, = tr.step(batch(), fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        wall = time.perf_counter() - t0
        raw1, enc1 = _bytes()
        tr.close()
        return ((raw1 - raw0) / STEPS, (enc1 - enc0) / STEPS,
                wall / STEPS * 1e3, losses)
    finally:
        srv.stop()


def run_sparse(fluid, np):
    """Embedding-row pull/push compression through the quantized client."""
    from paddle_tpu import observe
    from paddle_tpu.pserver import ParameterServer, PSClient
    from paddle_tpu.wire import ENCODED_BYTES_METRIC, RAW_BYTES_METRIC

    observe.reset_all()
    srv = ParameterServer("127.0.0.1:0").start()
    try:
        c = PSClient([srv.endpoint], comm_quant="int8")
        c.init_table("emb", rows=4000, width=16, dtype="float32",
                     init_low=-0.05, init_high=0.05, seed=3,
                     opt_type="sgd", lr=0.1, attrs={})
        rng = np.random.RandomState(9)
        for _ in range(8):
            ids = np.unique(rng.randint(0, 4000, 512).astype(np.int64))
            rows = c.prefetch_rows("emb", ids)
            c.push_sparse_grad("emb", ids,
                               rng.randn(*rows.shape).astype(np.float32)
                               * 0.01)
        reg = observe.default_registry()
        raw = enc = 0.0
        for cmd in ("prefetch", "push_sparse_grad"):
            raw += reg.get(RAW_BYTES_METRIC).value(cmd=cmd)
            enc += reg.get(ENCODED_BYTES_METRIC).value(cmd=cmd)
        c.close()
        return raw / enc if enc else 0.0
    finally:
        srv.stop()


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")  # env var alone is overridden

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers

    fluid.set_flag("observe", True)

    raw_b, raw_enc_b, ms_raw, losses_raw = run_sync_ps(
        fluid, layers, np, codec=None)
    q_raw_b, q_enc_b, ms_quant, losses_q = run_sync_ps(
        fluid, layers, np, codec="int8")
    sparse_x = run_sparse(fluid, np)

    # the raw run must account raw==encoded (codec off is byte-identity)
    assert abs(raw_b - raw_enc_b) < 1e-6, (raw_b, raw_enc_b)
    rec = {
        "wire_bytes_per_step_raw": round(q_raw_b, 1),
        "wire_bytes_per_step_encoded": round(q_enc_b, 1),
        "wire_compression_x": round(q_raw_b / q_enc_b, 2) if q_enc_b else 0.0,
        "wire_sync_ps_step_ms_raw": round(ms_raw, 3),
        "wire_sync_ps_step_ms_quant": round(ms_quant, 3),
        "wire_sparse_compression_x": round(sparse_x, 2),
        "wire_quant_loss_delta": round(float(np.mean(np.abs(
            np.asarray(losses_q) - np.asarray(losses_raw)))), 5),
    }
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
