#!/usr/bin/env python
"""master_node: run one fluid-elastic data master as its own process.

    # solo (legacy single master)
    python tools/master_node.py --endpoint 127.0.0.1:8800 \
        --snapshot /var/m/master.json

    # HA pair behind a 3-node quorum (start the standby FIRST)
    python tools/master_node.py --endpoint 127.0.0.1:8801 --standby \
        --quorum 127.0.0.1:7001,127.0.0.1:7002,127.0.0.1:7003
    python tools/master_node.py --endpoint 127.0.0.1:8800 \
        --replicate-to 127.0.0.1:8801 \
        --quorum 127.0.0.1:7001,127.0.0.1:7002,127.0.0.1:7003

    # operator probe: who rules, at what epoch, with what queues
    python tools/master_node.py --endpoint 127.0.0.1:8800 --status

Prints "ENDPOINT <host:port>" once listening (ephemeral-port friendly),
then parks until SIGTERM/SIGINT, which stops the node cleanly — its
snapshot (ark atomic idiom: embedded sha256 + retained `.prev` serial)
survives the restart, and a quorum-armed node's primacy lease simply
expires at the arbiters so the standby takes over.

`--status` (no server) connects to a RUNNING master and prints its
`ha_status` row — role, fencing epoch, issuing verdict, queue depths —
falling back to plain `stats` against a pre-elastic master.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--endpoint", default="127.0.0.1:0")
    ap.add_argument("--snapshot", default=None,
                    help="snapshot path (ark atomic; .prev serial "
                         "retained beside it)")
    ap.add_argument("--timeout-dur", type=float, default=20.0,
                    help="task lease duration (seconds)")
    ap.add_argument("--failure-max", type=int, default=3)
    ap.add_argument("--lease-s", type=float, default=2.0,
                    help="HA lease duration (replication heartbeat + "
                         "quorum primacy lease)")
    ap.add_argument("--standby", action="store_true",
                    help="start as a standby (promotes on the primary's "
                         "lease expiry — quorum-gated when --quorum is "
                         "given)")
    ap.add_argument("--no-auto-promote", action="store_true",
                    help="standby never self-promotes (operator-driven "
                         "failover)")
    ap.add_argument("--replicate-to", metavar="ENDPOINT", default=None,
                    help="start as the primary of an HA pair, forwarding "
                         "task-lifecycle records to this standby")
    ap.add_argument("--quorum", metavar="EP,EP,EP", default=None,
                    help="arbiter group endpoints (fluid-quorum); arms "
                         "fenced elections for the pair")
    ap.add_argument("--resource", default="master",
                    help="quorum resource name for the primacy lease")
    ap.add_argument("--pulse-port", type=int, default=None,
                    help="fluid-pulse health endpoint port (0 = "
                         "ephemeral; requires the observe flag, which "
                         "this CLI sets when given)")
    ap.add_argument("--status", action="store_true",
                    help="probe a RUNNING master at --endpoint and print "
                         "its epoch/queue row (no server)")
    args = ap.parse_args(argv)

    from paddle_tpu.master import Master, MasterClient

    if args.status:
        c = MasterClient(args.endpoint, failover_s=0.0)
        try:
            try:
                st = c.ha_status()
            except RuntimeError as e:
                if "unknown command" not in str(e):
                    raise
                st = dict(c.stats(), role="solo(pre-elastic)")
            print(" ".join(f"{k}={st[k]}" for k in sorted(st)))
        finally:
            c.close()
        return 0

    if args.pulse_port is not None:
        import paddle_tpu as fluid
        fluid.set_flag("observe", True)

    qeps = [e for e in (args.quorum or "").split(",") if e] or None
    node = Master(args.endpoint, snapshot_path=args.snapshot,
                  timeout_dur=args.timeout_dur,
                  failure_max=args.failure_max,
                  pulse_port=args.pulse_port)

    def arm():
        if args.standby:
            node.start_standby(lease_s=args.lease_s,
                               auto_promote=not args.no_auto_promote,
                               quorum_endpoints=qeps,
                               quorum_resource=args.resource)
        elif args.replicate_to:
            node.start_replication(args.replicate_to,
                                   lease_s=args.lease_s,
                                   quorum_endpoints=qeps,
                                   quorum_resource=args.resource)

    # arm the HA role BEFORE the listener serves task commands: with a
    # concrete port the endpoint (= the node's quorum identity) is
    # already known, and a recovering standby must never answer a
    # trainer's probe as a solo ruler in the start→arm window. Port 0
    # needs the bind to learn its identity first — ephemeral ports are
    # a tests-only convenience, not a pair deployment shape.
    ephemeral = args.endpoint.rsplit(":", 1)[-1] == "0"
    if not ephemeral:
        arm()
    node.start()
    if ephemeral:
        arm()
    print(f"ENDPOINT {node.endpoint}", flush=True)
    if node.pulse_port is not None:
        print(f"PULSE {node.pulse_port}", flush=True)

    done = threading.Event()

    def _stop(signum, frame):
        node.stop(resign=True)   # planned shutdown: hand the lease back
        done.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    done.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
