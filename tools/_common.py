"""Shared helper for the perf tools: compile a framework program's main
training step and return a jax `Compiled` for cost analysis / HLO dumps.

Centralizes the private-API dance (pick the largest cached step, collect
mut/const state, lower+compile) so a change to Executor internals breaks
one place, not three."""

from __future__ import annotations


def compile_main_step(exe, scope, feed):
    """exe must have run the program at least once with `feed`."""
    import numpy as np

    compiled = max(exe._cache.values(),
                   key=lambda c: len(c.program.global_block().ops))
    mut = {n: scope.find_var(n) for n in compiled.mut_names}
    const = {n: scope.find_var(n) for n in compiled.const_names}
    feeds = {k: feed[k] for k in sorted(feed)}
    return (compiled._step.lower(feeds, mut, const, np.uint32(0))
            .compile())
