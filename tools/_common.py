"""Shared helper for the perf tools: compile a framework program's main
training step and return a jax `Compiled` for cost analysis / HLO dumps.

Centralizes the private-API dance (pick the largest cached step, collect
mut/const state, lower+compile) so a change to Executor internals breaks
one place, not three."""

from __future__ import annotations


def compile_main_step(exe, scope, feed):
    """exe must have run the program at least once with `feed`."""
    import numpy as np

    compiled = max(exe._cache.values(),
                   key=lambda c: len(c.program.global_block().ops))
    mut = {n: scope.find_var(n) for n in compiled.mut_names}
    const = {n: scope.find_var(n) for n in compiled.const_names}
    feeds = {k: feed[k] for k in sorted(feed)}
    return (compiled._step.lower(feeds, mut, const, np.uint32(0))
            .compile())


def parse_flag(argv, name, default):
    """`--name value` or `--name=value`."""
    for i, a in enumerate(argv):
        if a == name and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith(name + "="):
            return a.split("=", 1)[1]
    return default


def slope_step_time(window, steps, lo=None, rounds=3, retries=2):
    """Two-point-slope per-step time, median of `rounds`: a window pays
    one ~90 ms tunnel sync regardless of length, so dividing a single
    window by its step count inflates per-step time (~8 ms at 12 steps);
    the slope is what a steady-state training loop sees.

    A tunnel stall landing in the LONG window of 2 of 3 rounds can push
    the median slope to zero or below; since callers divide by the
    result, a non-positive median is re-measured and ultimately an error,
    never a recorded throughput (round-4 advisor)."""
    lo = lo or max(2, steps // 4)
    med = None
    for _ in range(retries + 1):
        slopes = []
        for _ in range(rounds):
            t_lo, t_hi = window(lo), window(steps)
            slopes.append((t_hi - t_lo) / (steps - lo))
        med = sorted(slopes)[len(slopes) // 2]
        if med > 0:
            return med
    raise RuntimeError(
        f"slope_step_time: non-positive median slope {med!r} persisted "
        f"across {retries + 1} attempts (tunnel stall?) — refusing to "
        f"record a negative/inf throughput")
