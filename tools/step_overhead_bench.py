"""Host dispatch overhead per step: prepared vs unprepared (round 6).

The device side is near its ceiling (docs/PERF.md round 5), so this tool
measures the HOST side: the pure-python work `Executor.run()` does around
the jitted call each step. It is CPU-runnable (tiny MLP, in-process CPU
backend — same rationale as feeder_overlap_demo.py: dev-tunnel variance
exceeds the quantity under measurement, host dispatch is
backend-independent python).

Three dispatch paths over the SAME compiled entry, device time subtracted:

  legacy   : a faithful re-implementation of the pre-round-6 Executor.run
             body — per-step listen_and_serv op scan, flag-registry reads,
             compiler-option resolution, sorted cache-key rebuild, and a
             full O(state) scope gather (kept here as the measurement
             baseline; the shipped run() no longer does this)
  run      : the shipped Executor.run() — thin wrapper over a memoized
             PreparedProgram
  prepared : a held Executor.prepare() handle — feed conversion, cached
             state gather, jitted call, write-back only

  floor    : the bare jitted `_step` call with pre-gathered state — the
             irreducible jax dispatch + device time both paths pay

host overhead(path) = per-step wall(path) - floor;
the headline `step_overhead_reduction_x` = legacy overhead / prepared
overhead (acceptance: >= 2x). Prints ONE JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_program(fluid):
    """Tiny on purpose: host dispatch overhead is the quantity under
    measurement, so device time per step must be small against it (a
    16-wide 3-layer MLP + Adam still has ~20 state vars, so the O(state)
    scope gather the legacy path pays per step is realistic)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        h = fluid.layers.fc(input=h, size=16, act="relu")
        pred = fluid.layers.fc(input=h, size=4, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, startup, loss


def legacy_run(exe, cache, counters, order, program, feed, fetch_list, scope,
               np, jax, ir_mod, exec_mod):
    """The pre-round-6 Executor.run body, reproduced op for op as the
    'unprepared' measurement baseline (see module docstring)."""
    from paddle_tpu import flags as _flags

    ls = [op for op in program.global_block().ops
          if op.type == "listen_and_serv"]
    assert not ls
    fetch_names = [f.name if isinstance(f, ir_mod.Variable) else str(f)
                   for f in fetch_list]
    block = program.global_block()
    feed_arrays = exec_mod._convert_feed_dict(block, feed)
    copts = exec_mod.resolve_compiler_options(
        exe.place.jax_device().platform, program)
    cache_key = (program._uid, program._version,
                 tuple(sorted(feed_arrays)), tuple(fetch_names),
                 scope._uid, exe.amp, exe.check_nan_inf,
                 _flags.get_flag("dropout_impl"),
                 tuple(sorted(copts.items())) if copts else None,
                 program.random_seed)
    order.setdefault(program._uid, len(order))
    compiled = cache[cache_key]   # always warm in this bench
    counter = np.uint32(counters.get(program._uid, 0))
    counters[program._uid] = int(counter) + 1
    with jax.default_device(exe.place.jax_device()):
        return compiled.run(scope, feed_arrays, counter)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # env var alone is overridden
    # synchronous dispatch: with async CPU dispatch the host work of step
    # N overlaps (or blocks on) step N-1's execution depending on where
    # buffer releases land, which smears µs-scale host costs across
    # steps; synchronous calls make wall = host + device exactly, and the
    # shared floor subtraction removes the device part from every path
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core import executor as exec_mod
    from paddle_tpu.core import ir as ir_mod

    steps = int(os.environ.get("STEP_OVERHEAD_STEPS", "200"))
    n_rounds = int(os.environ.get("STEP_OVERHEAD_ROUNDS", "24"))
    warmup = 50

    main_p, startup, loss = build_program(fluid)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(32, 16).astype(np.float32),
            "y": rng.randint(0, 4, (32, 1)).astype(np.int64)}

    # bind + compile once through the public path; every timed path below
    # dispatches this same entry
    exe.run(main_p, feed=feed, fetch_list=[loss], scope=scope,
            return_numpy=False)
    entry = next(c for c in exe._cache.values()
                 if c.program is main_p)

    # seed the legacy path's cache with the same entry under the key the
    # legacy body computes, so it measures dispatch, not compilation
    from paddle_tpu import flags as _flags
    feed_arrays = exec_mod._convert_feed_dict(main_p.global_block(), feed)
    copts = exec_mod.resolve_compiler_options(
        exe.place.jax_device().platform, main_p)
    legacy_key = (main_p._uid, main_p._version,
                  tuple(sorted(feed_arrays)), (loss.name,),
                  scope._uid, exe.amp, exe.check_nan_inf,
                  _flags.get_flag("dropout_impl"),
                  tuple(sorted(copts.items())) if copts else None,
                  main_p.random_seed)
    legacy_cache = {legacy_key: entry}
    legacy_counters = dict(exe._run_counts)
    legacy_order = {}

    prepared = exe.prepare(main_p, fetch_list=[loss], scope=scope)

    warmed = set()

    def time_path(fn, n):
        if fn not in warmed:
            warmed.add(fn)
            for _ in range(warmup):
                out = fn()
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn()
        # one sync at the end: dispatch is synchronous (config above), so
        # per-step wall time already contains device time; the shared
        # floor subtraction removes it from every path identically
        np.asarray(out[0])
        return (time.perf_counter() - t0) / n * 1e6  # us/step

    def run_legacy():
        return legacy_run(exe, legacy_cache, legacy_counters, legacy_order,
                          main_p, feed, [loss], scope, np, jax, ir_mod,
                          exec_mod)

    def run_public():
        return exe.run(main_p, feed=feed, fetch_list=[loss], scope=scope,
                       return_numpy=False)

    def run_prepared():
        return prepared.run(feed, return_numpy=False)

    # floor: the bare jitted call. mut state is donated, so each call
    # refreshes its mut dict from the step's outputs — the minimal python
    # any dispatch path must do. The floor bypasses scope write-back, so
    # each floor window gathers fresh state first and restores the final
    # values to the scope after, keeping the other paths' reads live.
    state = {"mut": None, "const": None}

    def run_floor():
        fetches, new_state, _ = entry._step(feed_arrays, state["mut"],
                                            state["const"], np.uint32(0))
        state["mut"] = {n: new_state[n] for n in entry.mut_names}
        return fetches

    def floor_window(n):
        state["mut"], state["const"] = entry.gather_state(scope)
        us = time_path(run_floor, n)
        for k, v in state["mut"].items():
            scope.set_var(k, v)
        return us

    # many SHORT interleaved windows, per-path MINIMUM over rounds: this
    # box suffers multi-second interference bursts (shared core) that
    # inflate whole windows, and the noise is one-sided — interference
    # only ever ADDS time — so each path's minimum over many interleaved
    # windows is the clean per-step cost (the same argument bench.py
    # makes for its keep-the-max headline; timeit uses min likewise).
    rounds = {"legacy": [], "run": [], "prepared": [], "floor": []}
    for _ in range(n_rounds):
        rounds["floor"].append(floor_window(steps))
        rounds["prepared"].append(time_path(run_prepared, steps))
        rounds["run"].append(time_path(run_public, steps))
        rounds["legacy"].append(time_path(run_legacy, steps))
    med = {k: min(v) for k, v in rounds.items()}

    # the irreducible floor is BY DEFINITION <= every path's minimum; a
    # path window reading below the floor windows only proves the floor
    # estimate was inflated by drift, so take the min across all of them
    floor = min(med.values())
    over_legacy = max(med["legacy"] - floor, 0.0)
    over_run = max(med["run"] - floor, 0.0)
    over_prepared = max(med["prepared"] - floor, 0.0)
    # denominator clamped at ~the resolution of this measurement (2µs):
    # the prepared path's overhead routinely lands inside window noise,
    # and a literal zero would turn a best-case result into a 0.0 ratio
    # that reads as a failed measurement. The clamp makes the reported
    # reduction CONSERVATIVE (never inflated by a tiny denominator).
    reduction = over_legacy / max(over_prepared, 2.0)
    result = {
        "steps_per_window": steps,
        "floor_us": round(floor, 2),
        "legacy_us": round(med["legacy"], 2),
        "run_us": round(med["run"], 2),
        "prepared_us": round(med["prepared"], 2),
        "step_overhead_us_unprepared": round(over_legacy, 2),
        "step_overhead_us_run": round(over_run, 2),
        "step_overhead_us": round(over_prepared, 2),
        "step_overhead_reduction_x": round(reduction, 2),
    }
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
