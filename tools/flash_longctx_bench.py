"""Flash-vs-unfused transformer pairs at long context (round-4 item 5).

Slope-timed (two-point windows, median of 3) training-step throughput of
the full transformer at seq {2048, 4096, 8192}, fused_attention on/off.
Prints tok/s per config and the flash/unfused ratio per seq.

Usage: python tools/flash_longctx_bench.py [--points "8x2048,4x4096,2x8192"]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def bench(fluid, models, jax, batch_size, seq_len, fused, steps=8, warmup=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        feeds, fetches = models.transformer.build(seq_len=seq_len,
                                                  fused_attention=fused)
        loss = fetches["loss"]
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0), amp=True)
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    batch = {k: jax.device_put(rng.randint(1, 30000, (batch_size, seq_len))
                               .astype(np.int32))
             for k in ("src_word", "trg_word", "lbl_word")}
    for _ in range(warmup):
        out = exe.run(main, feed=batch, fetch_list=[loss],
                      return_numpy=False, scope=scope)
    np.asarray(out[0])

    def window(n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = exe.run(main, feed=batch, fetch_list=[loss],
                          return_numpy=False, scope=scope)
        np.asarray(out[0])
        return time.perf_counter() - t0

    from tools._common import slope_step_time
    dt = slope_step_time(window, steps)
    return batch_size * seq_len / dt, dt


def main():
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import models

    from tools._common import parse_flag
    points = parse_flag(sys.argv[1:], "--points", "8x2048,4x4096,2x8192")

    for pt in points.split(","):
        b, s = (int(x) for x in pt.strip().split("x"))
        tok_f, dt_f = bench(fluid, models, jax, b, s, fused=True)
        try:
            tok_u, dt_u = bench(fluid, models, jax, b, s, fused=False)
        except Exception as e:
            # at seq 8192 the unfused path needs ~37.5 GB for the O(T^2)
            # score tensors — more than the chip's HBM. That OOM IS the
            # capability gap flash closes; record it as such.
            msg = "OOM" if "memory" in str(e).lower() else type(e).__name__
            print(f"bs{b} seq{s}: flash {tok_f:,.0f} tok/s "
                  f"({dt_f * 1e3:.1f} ms) | unfused {msg} "
                  f"| flash/unfused inf")
            continue
        print(f"bs{b} seq{s}: flash {tok_f:,.0f} tok/s ({dt_f * 1e3:.1f} ms) "
              f"| unfused {tok_u:,.0f} tok/s ({dt_u * 1e3:.1f} ms) "
              f"| flash/unfused {tok_f / tok_u:.2f}x")


if __name__ == "__main__":
    main()
