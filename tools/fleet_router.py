#!/usr/bin/env python
"""fluid-fleet router CLI: run (or drive) the multi-replica serving tier.

    # spawn a local 3-replica fleet over one model dir and route forever
    python tools/fleet_router.py --spawn 3 --model-dir /models/m

    # attach to already-running replicas (tools/fleet_replica.py)
    python tools/fleet_router.py --attach 127.0.0.1:7001,127.0.0.1:7002

    # one-shot coordinated, version-skew-free swap across the fleet
    python tools/fleet_router.py --attach ... --swap /models/m_v2 --exit

Prints `CONTROL <endpoint>` (replicas heartbeat there) and a MEMBERS
status line per poll interval; SIGINT/SIGTERM shuts the fleet down
cleanly. The serious drills live in `tools/serve_loadgen.py --replicas`
and `tools/chaos_drill.py --scenario replica_kill`; this CLI is the
operator's on-ramp.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def spawn_replicas(n, model_dir, router_ep, extra_args=(), name="m",
                   pulse=False, device_ms=0.0, lease_s=3.0,
                   rid_prefix="r"):
    """Start n `tools/fleet_replica.py` subprocesses against `router_ep`;
    returns the Popen list after every worker printed READY.

    Mixed fluid-torrent pools: call twice with distinct `rid_prefix`es
    and `extra_args=("--role", "prefill")` / `("--role", "decode")` —
    replica ids must not collide across the calls."""
    workers = []
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fleet_replica.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for i in range(n):
        cmd = [sys.executable, tool, "--model-dir", model_dir,
               "--name", name, "--router", router_ep,
               "--replica-id", f"{rid_prefix}{i}",
               "--lease-s", str(lease_s)]
        if pulse:
            cmd += ["--pulse-port", "0"]
        if device_ms:
            cmd += ["--device-ms", str(device_ms)]
        cmd += list(extra_args)
        workers.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                        text=True, env=env))
    import queue as _queue

    # one reader thread per worker, lines drained into a queue: the
    # startup wait below then has a REAL deadline (a bare readline()
    # blocks forever on a wedged-but-alive worker, and select() lies
    # once readline's buffered read-ahead has swallowed later lines);
    # the thread also keeps draining stdout afterwards so a chatty
    # worker can never block on a full pipe
    def _reader(w, q):
        try:
            for line in w.stdout:
                q.put(line.strip())
        finally:
            q.put(None)          # EOF sentinel

    lines: dict = {}
    for w in workers:
        q = _queue.Queue()
        lines[w.pid] = q
        threading.Thread(target=_reader, args=(w, q), daemon=True).start()
    for w in workers:
        deadline = time.time() + 120
        ready = False
        while time.time() < deadline:
            try:
                line = lines[w.pid].get(timeout=1.0)
            except _queue.Empty:
                if w.poll() is not None:
                    raise RuntimeError(
                        f"replica worker died at startup "
                        f"(rc={w.returncode})")
                continue
            if line is None:
                raise RuntimeError(
                    f"replica worker died at startup (rc={w.poll()})")
            if line == "READY":
                ready = True
                break
        if not ready:
            raise RuntimeError("replica worker never reported READY "
                               "within 120s")
    return workers


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--spawn", type=int, default=0,
                    help="spawn N local replica workers (needs "
                    "--model-dir)")
    ap.add_argument("--model-dir", default=None)
    ap.add_argument("--name", default="m")
    ap.add_argument("--attach", default=None,
                    help="comma-separated replica RPC endpoints to add")
    ap.add_argument("--lease-s", type=float, default=3.0)
    ap.add_argument("--poll-interval-s", type=float, default=0.5)
    ap.add_argument("--pulse-port", type=int, default=None,
                    help="arm the ROUTER's own fluid-pulse health plane "
                    "(turns the observe flag on)")
    ap.add_argument("--replica-pulse", action="store_true",
                    help="spawned replicas arm their own pulse (the "
                    "router then polls real HTTP /readyz)")
    ap.add_argument("--device-ms", type=float, default=0.0,
                    help="spawned replicas' simulated device time "
                    "(rehearsal rigs; see fleet_replica.py)")
    ap.add_argument("--swap", metavar="DIR", default=None,
                    help="run one coordinated fleet swap to DIR")
    ap.add_argument("--exit", dest="exit_after", action="store_true",
                    help="exit after startup (and --swap, if given) "
                    "instead of routing forever")
    args = ap.parse_args(argv)

    if args.spawn and not args.model_dir:
        ap.error("--spawn needs --model-dir")

    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as fluid
    from paddle_tpu import fleet

    if args.pulse_port is not None:
        fluid.set_flag("observe", True)

    router = fleet.FleetRouter(fleet.RouterConfig(
        lease_s=args.lease_s, poll_interval_s=args.poll_interval_s,
        pulse_port=args.pulse_port)).start()
    print(f"CONTROL {router.control_endpoint}", flush=True)
    if router.pulse_port is not None:
        print(f"PULSE {router.pulse_port}", flush=True)

    workers = []
    try:
        if args.spawn:
            workers = spawn_replicas(
                args.spawn, args.model_dir, router.control_endpoint,
                name=args.name, pulse=args.replica_pulse,
                device_ms=args.device_ms, lease_s=args.lease_s)
        for ep in (args.attach or "").split(","):
            if ep:
                router.add_replica(ep)
        # one poll round so MEMBERS below reflects reality
        time.sleep(max(args.poll_interval_s, 0.2))
        if args.swap:
            report = router.swap(args.name, args.swap)
            print(f"SWAP {report}", flush=True)

        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        while not args.exit_after and not stop.is_set():
            mem = router.members()
            ready = sum(1 for m in mem.values() if m["ready"])
            print(f"MEMBERS {len(mem)} ready={ready} "
                  f"{sorted(mem)}", flush=True)
            stop.wait(max(2.0, args.poll_interval_s * 4))
        return 0
    finally:
        for w in workers:
            if w.poll() is None:
                w.terminate()
        for w in workers:
            try:
                w.wait(timeout=10)
            except subprocess.TimeoutExpired:
                w.kill()
        router.close()


if __name__ == "__main__":
    sys.exit(main())
