#!/usr/bin/env python
"""fluid-horizon observatory: scrape a live fleet's pulse endpoints
into one queryable time-series view.

    # live table, refreshed each scrape interval (ctrl-C to stop)
    python tools/observatory.py replica0=8471 replica1=8472 ps=9000 --watch

    # scrape a few rounds, print one machine-readable snapshot
    python tools/observatory.py replica0=8471 --rounds 5 --json

    # fetch every target's /trace ring, stitch (skew-corrected, with
    # causal flow arrows) into one chrome://tracing timeline
    python tools/observatory.py replica0=8471 ps=9000 --dump-trace fleet.json

Targets are `job=url` pairs; a bare port means 127.0.0.1. Everything
rides the round-13 pulse endpoints (`/metrics`, `/trace`) — processes
need `observe.start_pulse()`, nothing else.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def parse_targets(specs):
    targets = []
    for i, spec in enumerate(specs):
        if "=" in spec:
            job, url = spec.split("=", 1)
        else:
            job, url = f"target{i}", spec
        targets.append((job, url))
    if not targets:
        raise SystemExit("no targets; pass job=url (or job=port) pairs")
    return targets


def _fmt(v, scale=1.0, suffix=""):
    if v is None:
        return "-"
    return f"{v * scale:.1f}{suffix}"


def overview_table(sc, window_s):
    o = sc.fleet_overview(window_s=window_s)
    rows = [
        ("targets up", f"{o['targets_up']}/{o['targets']}"),
        ("serve qps", _fmt(o["serve_qps"])),
        ("fleet qps", _fmt(o["fleet_qps"])),
        ("request p50", _fmt(o["request_p50_us"], 1e-3, " ms")),
        ("request p99", _fmt(o["request_p99_us"], 1e-3, " ms")),
        ("decode occupancy", _fmt(o["decode_occupancy"])),
        ("max repl lag", _fmt(o["max_ps_replication_lag"])),
        ("ps rpc qps", _fmt(o["ps_rpc_qps"])),
        ("master todo", _fmt(o["master_tasks_todo"])),
    ]
    width = max(len(k) for k, _ in rows)
    return "\n".join(f"  {k:<{width}}  {v}" for k, v in rows)


def dump_trace(sc, out_path):
    from paddle_tpu.observe import scrape, stitch

    paths, skipped = [], []
    with tempfile.TemporaryDirectory(prefix="observatory_") as td:
        for t in sc.targets():
            job, url = t["job"], t["url"]
            try:
                doc = scrape.fetch_trace(url)
            except Exception as e:
                skipped.append((job, f"{type(e).__name__}: {e}"))
                continue
            p = os.path.join(td, f"{job}.json")
            with open(p, "w") as f:
                json.dump(doc, f)
            paths.append(p)
        for job, why in skipped:
            print(f"observatory: skipping {job}: {why}", file=sys.stderr)
        if not paths:
            raise SystemExit("no target served a /trace ring")
        _doc, stats = stitch.stitch_traces(paths, out_path=out_path)
    print(f"wrote {out_path}: {stats['spans_out']} spans from "
          f"{len(paths)} process(es), {stats['edges']} cross-process "
          f"edge(s), {stats['orphans']} orphan(s), "
          f"skew_us={stats['skew_us']}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="observatory",
        description="scraping observatory over fluid-pulse endpoints")
    ap.add_argument("targets", nargs="*", metavar="JOB=URL",
                    help="pulse endpoints (bare port = 127.0.0.1)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="seconds between scrape rounds")
    ap.add_argument("--window", type=float, default=30.0,
                    help="query window for rates/percentiles (s)")
    ap.add_argument("--rounds", type=int, default=3,
                    help="scrape rounds before a one-shot output")
    ap.add_argument("--watch", action="store_true",
                    help="continuous table (ctrl-C to stop)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print a snapshot of every series + overview")
    ap.add_argument("--dump-trace", metavar="OUT",
                    help="stitch every target's /trace ring into OUT")
    args = ap.parse_args(argv)

    from paddle_tpu.observe import scrape

    sc = scrape.Scraper(parse_targets(args.targets),
                        interval_s=args.interval)

    if args.dump_trace:
        return dump_trace(sc, args.dump_trace)

    if args.watch:
        sc.start()
        try:
            while True:
                time.sleep(args.interval)
                print(f"\n== observatory @ round {sc.rounds()} "
                      f"(window {args.window:g}s) ==")
                print(overview_table(sc, args.window))
        except KeyboardInterrupt:
            return 0
        finally:
            sc.stop()

    for _ in range(max(1, args.rounds)):
        sc.poll_once()
        time.sleep(args.interval)
    if args.as_json:
        print(json.dumps(sc.snapshot(window_s=args.window), indent=2,
                         sort_keys=True))
    else:
        print(overview_table(sc, args.window))
    return 0


if __name__ == "__main__":
    sys.exit(main())
