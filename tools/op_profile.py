#!/usr/bin/env python
"""op_profile: the per-op cost table of a model, next to measured step time.

Static per-op FLOPs/bytes/memory from `analysis.cost_model` (the GDP-style
cost view of the dataflow graph), attributed against the measured
device_compute phase of a short observed run — so "which op is my step
time" has an answer without a device profiler attached:

    python tools/op_profile.py --model transformer --topk 15
    python tools/op_profile.py --model mlp --json
    python tools/op_profile.py --xla-check      # exit 1 if the static
        # total disagrees with XLA's compiled cost_analysis() by >10%

Models: mlp (tiny fc stack), transformer (book transformer, scaled-down
config by default; --full-size for the real base config), resnet
(ResNet-18-ish; conv rules). The est_time column is `flops_share x
measured device_compute` — exact for a compute-bound step, an upper
bound for a bandwidth-bound one (compare against the bytes column).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_mlp(fluid, layers, batch):
    x = layers.data(name="x", shape=[64], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="int64")
    h = layers.fc(input=x, size=128, act="relu")
    h = layers.fc(input=h, size=64, act="relu")
    pred = layers.fc(input=h, size=10, act="softmax")
    loss = layers.mean(layers.cross_entropy(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    import numpy as np
    feed = {"x": np.random.RandomState(0).randn(batch, 64)
            .astype(np.float32),
            "y": np.random.RandomState(1).randint(0, 10, (batch, 1))
            .astype(np.int64)}
    return loss, feed


def build_transformer(fluid, layers, batch, full_size=False):
    import numpy as np

    from paddle_tpu import models
    kw = {} if full_size else dict(
        src_vocab_size=1000, trg_vocab_size=1000, seq_len=32, n_layer=2,
        n_head=2, d_model=64, d_inner=128)
    feeds, fetches = models.transformer.build(
        dropout_rate=0.0, is_test=True, fused_attention=False, **kw)
    seq = 256 if full_size else 32
    vocab = 30000 if full_size else 1000
    rng = np.random.RandomState(0)
    feed = {k: rng.randint(1, vocab - 1, (batch, seq)).astype(np.int64)
            for k in ("src_word", "trg_word", "lbl_word")}
    return fetches["loss"], feed


def build_resnet(fluid, layers, batch):
    import numpy as np

    from paddle_tpu import models
    feeds, fetches = models.resnet.build(class_dim=10, depth=18,
                                         data_format="NHWC")
    loss = fetches["loss"]
    fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {"image": rng.rand(batch, 224, 224, 3).astype(np.float32),
            "label": rng.randint(0, 10, (batch, 1)).astype(np.int32)}
    return loss, feed


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="per-op FLOPs/bytes cost table + measured step share")
    ap.add_argument("--model", choices=("mlp", "transformer", "resnet"),
                    default="transformer")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=3,
                    help="observed steps for the measured time column")
    ap.add_argument("--topk", type=int, default=15)
    ap.add_argument("--full-size", action="store_true",
                    help="transformer: the real base config (slow compile)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable compact summary on stdout")
    ap.add_argument("--xla-check", action="store_true",
                    help="compare the static total against XLA "
                         "cost_analysis(); exit 1 beyond 10%%")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import layers, observe
    from paddle_tpu.analysis import cost_model

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup), fluid.unique_name.guard():
        loss, feed = {
            "mlp": build_mlp,
            "transformer": lambda *a: build_transformer(
                *a, full_size=args.full_size),
            "resnet": build_resnet,
        }[args.model](fluid, layers, args.batch)

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)

    fluid.set_flag("observe", True)
    prepared = exe.prepare(main_p, fetch_list=[loss], scope=scope)
    for _ in range(max(args.steps, 1)):
        prepared.run(dict(feed))
    summ = observe.get_steplog().phase_summary()
    # measured device_compute per steady step (the binding step's compile
    # rides inside device_compute — drop it via the mean of the rest when
    # more than one step ran)
    steps = [s for s in observe.get_steplog().recent(64)
             if "bind" not in s.phases]
    dev_s = (sum(s.phases.get("device_compute", 0.0) for s in steps)
             / len(steps)) if steps else 0.0

    report = cost_model.estimate_cost(
        main_p, {k: v.shape for k, v in feed.items()})

    xla = None
    if args.xla_check or args.json:
        try:
            xla = cost_model.xla_flops(exe, scope, feed)
        except Exception as e:
            print(f"WARNING: xla cross-check failed ({e!r})",
                  file=sys.stderr)

    if args.json:
        out = report.as_dict(args.topk)
        out["model"] = args.model
        out["batch"] = args.batch
        out["measured_device_compute_us"] = round(dev_s * 1e6, 2)
        out["observed_steps"] = summ["steps"]
        if xla:
            out["xla_flops"] = xla
            out["xla_agreement"] = round(report.total_flops / xla, 4)
        print(json.dumps(out, sort_keys=True))
    else:
        print(f"model={args.model} batch={args.batch} "
              f"(measured device_compute "
              f"{dev_s * 1e6:.0f} us/step over {len(steps)} steady steps)")
        print(report.table(args.topk, step_time_s=dev_s or None))

    if args.xla_check:
        if not xla:
            print("XLA-CHECK FAILED: no cost_analysis flops available",
                  file=sys.stderr)
            return 1
        ratio = report.total_flops / xla
        ok = 0.9 <= ratio <= 1.1
        print(f"xla-check: static={report.total_flops:.4g} "
              f"xla={xla:.4g} ratio={ratio:.3f} "
              f"{'OK' if ok else 'OUTSIDE 10%'}", file=sys.stderr)
        if not ok:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
