"""Compare the framework-compiled transformer train step against the
hand-written JAX yardstick (tools/yardstick_transformer.py): optimized-HLO
op histograms side by side, plus wall-clock timing when run on a device.

Usage:
    JAX_PLATFORMS=cpu python tools/hlo_diff.py          # structure only
    python tools/hlo_diff.py --time                      # + timing (TPU)

The histogram diff localizes Program/IR-layer overhead: extra `convert`s
point at AMP casting churn, extra `transpose`/`reshape` at layout churn,
extra `fusion`s at fragmentation, `rng`/`custom-call` rows at dropout
implementation differences (docs/PERF.md "Remaining gap" section).
"""

from __future__ import annotations

import collections
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def hlo_histogram(text: str) -> collections.Counter:
    ops = collections.Counter()
    for line in text.splitlines():
        line = line.strip()
        m = re.match(r"(?:ROOT )?[%\w.\-]+ = \S+ ([\w\-]+)\(", line)
        if m:
            ops[m.group(1)] += 1
    return ops


def framework_step(batch_size=64, seq_len=256):
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        feeds, fetches = models.transformer.build(seq_len=seq_len,
                                                  fused_attention=False)
        loss = fetches["loss"]
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0), amp=True)
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    batch = {k: rng.randint(1, 30000, (batch_size, seq_len)).astype(np.int32)
             for k in ("src_word", "trg_word", "lbl_word")}

    def run():
        return exe.run(main, feed=batch, fetch_list=[loss],
                       return_numpy=False, scope=scope)

    out = run()  # compile
    from tools._common import compile_main_step
    return compile_main_step(exe, scope, batch), run, out


def yardstick_step():
    import jax
    from tools import yardstick_transformer as y

    params = y.init_params(0)
    opt = y.adam_init(params)
    batch = y.make_batch()
    key = jax.random.key(0)
    lowered = y.train_step.lower(params, opt, batch, key)
    state = {"p": params, "o": opt}

    def run():
        state["p"], state["o"], loss = y.train_step(state["p"], state["o"],
                                                    batch, key)
        return [loss]

    return lowered.compile(), run, run()


def time_steps(run, steps=12):
    out = run()
    np.asarray(out[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        out = run()
    np.asarray(out[0])
    return (time.perf_counter() - t0) / steps


def main():
    fw_compiled, fw_run, _ = framework_step()
    ys_compiled, ys_run, _ = yardstick_step()

    fw_hist = hlo_histogram(fw_compiled.as_text())
    ys_hist = hlo_histogram(ys_compiled.as_text())

    keys = sorted(set(fw_hist) | set(ys_hist),
                  key=lambda k: -(fw_hist[k] - ys_hist[k]))
    print(f"{'hlo op':28} {'framework':>10} {'yardstick':>10} {'delta':>7}")
    for k in keys:
        d = fw_hist[k] - ys_hist[k]
        if fw_hist[k] or ys_hist[k]:
            print(f"{k:28} {fw_hist[k]:>10} {ys_hist[k]:>10} {d:>+7}")
    print(f"{'TOTAL':28} {sum(fw_hist.values()):>10} "
          f"{sum(ys_hist.values()):>10} "
          f"{sum(fw_hist.values()) - sum(ys_hist.values()):>+7}")

    for label, compiled in (("framework", fw_compiled),
                            ("yardstick", ys_compiled)):
        try:
            ca = compiled.cost_analysis()
            print(f"{label}: flops={ca.get('flops', 0):.3e} "
                  f"bytes={ca.get('bytes accessed', 0):.3e}")
        except Exception as e:
            print(f"{label}: cost analysis unavailable ({e!r})")

    if "--time" in sys.argv:
        fw_ms = time_steps(fw_run) * 1e3
        ys_ms = time_steps(ys_run) * 1e3
        print(f"framework {fw_ms:.1f} ms/step | yardstick {ys_ms:.1f} ms/step "
              f"| overhead {fw_ms / ys_ms:.2f}x")


if __name__ == "__main__":
    main()
