#!/usr/bin/env python
"""fluid-fleet replica worker: one serving process of the fleet.

Loads a model dir into an InferenceServer, fronts it with a
fleet.ReplicaServer on a TCP endpoint, heartbeats the router's control
endpoint, and (optionally) arms the fluid-pulse health plane so the
router can poll the real HTTP /readyz. Prints, one per line, for the
parent process to read:

    REPLICA <rpc endpoint>
    PULSE <port>            (only with --pulse-port)
    READY

Runs until SIGTERM (clean close: leaves the fleet, drains) or SIGKILL
(the chaos drill's case: the router finds out the hard way).

    python tools/fleet_replica.py --model-dir /models/m --router HOST:PORT
    python tools/fleet_replica.py --model-dir /models/dfm \
        --sparse-endpoints host:4471,host:4472 --sparse-quant int8

`--device-ms` is the CPU-rehearsal knob (see ReplicaServer): it sleeps
that long per request in place of the TPU device time a real replica
spends off the host CPU, letting a single-core rig measure router/RPC
scaling honestly. Must be 0 (default) in real deployments.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--name", default="m", help="served model name")
    ap.add_argument("--endpoint", default="127.0.0.1:0",
                    help="RPC endpoint to serve on (default ephemeral)")
    ap.add_argument("--replica-id", default=None)
    ap.add_argument("--router", default=None,
                    help="router control endpoint to heartbeat")
    ap.add_argument("--lease-s", type=float, default=3.0)
    ap.add_argument("--buckets", default="1,2,4,8", help="rows ladder")
    ap.add_argument("--batch-timeout-ms", type=float, default=2.0)
    ap.add_argument("--max-queue", type=int, default=512)
    ap.add_argument("--pulse-port", type=int, default=None,
                    help="arm fluid-pulse on this port (0 = ephemeral); "
                    "turns the observe flag on")
    ap.add_argument("--watch-interval-s", type=float, default=0.0,
                    help="> 0: poll the model dir for atomic pushes "
                    "(self-swap outside coordinated swaps)")
    ap.add_argument("--sparse-endpoints", default=None,
                    help="pserver endpoints holding the model's "
                    "distributed lookup tables (comma-separated)")
    ap.add_argument("--sparse-quant", default=None,
                    help="wire codec for row pulls (int8/bf16)")
    ap.add_argument("--sparse-cache-rows", type=int, default=65536)
    ap.add_argument("--device-ms", type=float, default=0.0,
                    help="REHEARSAL ONLY: simulated per-request device "
                    "time (sleep) — see ReplicaServer docstring")
    ap.add_argument("--role", default="both",
                    choices=("prefill", "decode", "both"),
                    help="fluid-torrent pool this replica advertises "
                    "(routing hint; 'both' = all traffic)")
    ap.add_argument("--sim-prefill-us-per-token", type=float, default=0.0,
                    help="REHEARSAL ONLY: simulated per-token prefill "
                    "device time (sleep inside the engine loop) — "
                    "models the compute-bound prefill phase on CPU rigs")
    ap.add_argument("--sim-decode-step-us", type=float, default=0.0,
                    help="REHEARSAL ONLY: simulated per-step decode "
                    "device time — models the memory-bound decode phase")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="turn the observe flag on and export this "
                    "process's chrome trace here at clean shutdown "
                    "(fluid-horizon stitches one per fleet process)")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import fleet, serve
    from paddle_tpu.observe import xray

    rid = args.replica_id or f"r{os.getpid()}"
    xray.set_process_name(f"replica-{rid}")
    if args.pulse_port is not None or args.trace_out:
        fluid.set_flag("observe", True)

    srv = serve.InferenceServer(
        fluid.CPUPlace(),
        serve.ServeConfig(batch_timeout_ms=args.batch_timeout_ms,
                          max_queue=args.max_queue,
                          watch_interval_s=args.watch_interval_s or 2.0,
                          pulse_port=args.pulse_port,
                          simulate_prefill_us_per_token=(
                              args.sim_prefill_us_per_token),
                          simulate_decode_step_us=args.sim_decode_step_us))
    sparse = None
    if args.sparse_endpoints:
        sparse = fleet.SparseServeConfig(
            [e for e in args.sparse_endpoints.split(",") if e],
            comm_quant=args.sparse_quant,
            cache_rows=args.sparse_cache_rows)
    # generative dirs (a __decode__ sidecar in the manifest) derive
    # their ladder from the decode signature; an explicit rows ladder
    # is the dense one-shot path's knob only
    from paddle_tpu.serve.registry import read_decode_signature
    ladder = None
    if read_decode_signature(args.model_dir) is None:
        ladder = serve.BucketLadder(
            rows=tuple(int(b) for b in args.buckets.split(",")))
    srv.add_model(args.name, args.model_dir, ladder=ladder, sparse=sparse)
    if args.watch_interval_s > 0:
        srv.start_watch(args.watch_interval_s)

    rep = fleet.ReplicaServer(srv, endpoint=args.endpoint, replica_id=rid,
                              router_endpoint=args.router,
                              lease_s=args.lease_s,
                              simulate_device_ms=args.device_ms,
                              role=args.role).start()
    print(f"REPLICA {rep.endpoint}", flush=True)
    if srv.pulse_port is not None:
        print(f"PULSE {srv.pulse_port}", flush=True)
    print("READY", flush=True)

    done = threading.Event()

    def _term(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    done.wait()
    rep.close()
    if args.trace_out:
        from paddle_tpu.observe import get_tracer
        get_tracer().export_chrome(args.trace_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
