#!/usr/bin/env python
"""fluid-sentry: concurrency lint CLI over the repo's own Python.

    # sweep the default target (paddle_tpu/) against the baseline
    python tools/race_lint.py

    # specific files or trees
    python tools/race_lint.py paddle_tpu/fleet/router.py paddle_tpu/master/

    # machine-readable findings
    python tools/race_lint.py --format json

    # show everything, including baselined residue
    python tools/race_lint.py --no-baseline

    # accept the current findings as the reviewed residue
    python tools/race_lint.py --update-baseline

Exit status: 0 = clean (new-ERROR-free; warnings tolerated unless
--strict), 1 = NEW findings above the threshold, 2 = usage failure —
mirroring tools/paddle_lint.py.

The sweep is `paddle_tpu.analysis.concurrency`: lock-discipline race
detection over `# guarded_by:` annotations (with majority-usage
inference), the cross-class acquires-while-holding deadlock graph, and
hold-time hazards (blocking calls under a lock). The baseline
(tools/race_lint_baseline.json) pins triaged residue by line-free key,
so CI fails only on findings that are actually new. Baselined entries
carry a mandatory `note` naming why they are accepted; stale entries
(baselined but no longer reported) are listed so the file stays honest.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# the sweep is pure AST work — never initialize a TPU
os.environ.setdefault("JAX_PLATFORMS", "cpu")

DEFAULT_BASELINE = os.path.join(_REPO, "tools", "race_lint_baseline.json")


def _collect(paths):
    from paddle_tpu.analysis import concurrency as cc

    files = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d != "__pycache__"]
                files += [os.path.join(dirpath, f)
                          for f in sorted(filenames) if f.endswith(".py")]
        elif p.endswith(".py"):
            files.append(p)
        else:
            raise SystemExit(f"not a python file or directory: {p!r}")
    if not files:
        raise SystemExit("no .py files to analyze")
    return cc.analyze_paths(files, root=_REPO)


def _load_baseline(path):
    if not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return {e["key"]: e.get("note", "") for e in doc.get("entries", [])}
    except (OSError, json.JSONDecodeError, KeyError, TypeError) as e:
        raise SystemExit(f"cannot load baseline {path!r}: {e}")


def _write_baseline(path, diags, old):
    from paddle_tpu.analysis.concurrency import baseline_key

    entries, seen = [], set()
    for d in diags:
        key = baseline_key(d)
        if key in seen:
            continue
        seen.add(key)
        entries.append({
            "key": key,
            "note": old.get(key, "TODO: triage — explain why this is "
                                 "by-design or file the fix"),
        })
    doc = {
        "version": 1,
        "comment": "Reviewed concurrency-lint residue (tools/race_lint.py)."
                   " Every entry needs a triage note: CI "
                   "(tests/test_race_lint.py) fails on findings missing "
                   "from this file. Keys are line-free "
                   "(code path Class.member detail) so they survive "
                   "unrelated edits. Regenerate with --update-baseline; "
                   "notes are preserved.",
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    return len(entries)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="race_lint",
        description="concurrency static analysis: lock-discipline races, "
                    "deadlock cycles, hold-time hazards")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_REPO, "paddle_tpu")],
                    help="files or trees to analyze "
                         "(default: paddle_tpu/)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="reviewed-residue file (default: "
                         "tools/race_lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baselined or not")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "(existing triage notes are preserved)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on new warnings too")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import Severity
    from paddle_tpu.analysis.concurrency import baseline_key

    diags = _collect(args.paths)
    # INFO (guard-inference proposals) never gates and is never baselined
    gating = [d for d in diags if d.severity >= Severity.WARNING]
    info = [d for d in diags if d.severity < Severity.WARNING]

    old = {} if args.no_baseline else _load_baseline(args.baseline)

    if args.update_baseline:
        n = _write_baseline(args.baseline, gating,
                            _load_baseline(args.baseline))
        print(f"wrote {n} entries to {args.baseline}")
        return 0

    new = [d for d in gating if baseline_key(d) not in old]
    seen_keys = {baseline_key(d) for d in gating}
    stale = sorted(k for k in old if k not in seen_keys)

    n_err = sum(d.severity == Severity.ERROR for d in new)
    n_warn = sum(d.severity == Severity.WARNING for d in new)

    if args.format == "json":
        print(json.dumps({
            "errors": n_err, "warnings": n_warn,
            "baselined": len(gating) - len(new), "stale": stale,
            "diagnostics": [dict(d.to_dict(), path=d.path, line=d.line,
                                 key=baseline_key(d)) for d in new],
            "proposals": [dict(d.to_dict(), path=d.path, line=d.line)
                          for d in info],
        }, indent=2))
    else:
        for d in new:
            print(f"{d.severity}: [{d.code}] {d.path}:{d.line}: "
                  f"{d.message}")
        for d in info:
            print(f"{d.severity}: [{d.code}] {d.path}:{d.line}: "
                  f"{d.message}")
        for k in stale:
            print(f"stale baseline entry (no longer reported): {k}")
        print(f"{n_err} new error(s), {n_warn} new warning(s), "
              f"{len(gating) - len(new)} baselined, "
              f"{len(info)} proposal(s), {len(stale)} stale")
    return 1 if (n_err or (args.strict and n_warn)) else 0


if __name__ == "__main__":
    sys.exit(main())
