#!/usr/bin/env python
"""Print/export the fluid-scope telemetry of an instrumented run.

Runs a small prepared-program training loop on the CPU backend with the
`observe` flag on, then dumps the metrics registry, the step-phase
summary, and the recompilation observatory. The interesting CI mode:

    python tools/telemetry_dump.py --assert-no-recompiles
        exit 0 when the steady-state run compiled each program exactly
        once (only `first_call` events)

    python tools/telemetry_dump.py --assert-no-recompiles --two-shapes
        feeds the SAME model two distinct batch shapes -> the second
        shape is a jit cache miss attributed `feed_shape` -> exit 1.
        This is the runtime counterpart of fluid-lint's static
        feed-shape recompile-hazard warning (PR 2): the lint predicts
        the hazard, the observatory proves whether it fired.

Serving runs (serve/) tag their events with source="serving": a failure
whose cause is `padding_bucket` means the bucket ladder is mis-sized
(the planner emitted a shape warmup never compiled — fix the ladder),
while `feed_shape`/anything else on a serving source is a genuine
compile-cache bug. Warmup compiles (`warmup`, `first_call`) are expected
and never fail the assertion.

Other output modes: --format json (default) | prom (Prometheus text
exposition) | table (human summary — includes the fluid-wire
per-command compression table, raw -> on-wire bytes with the ratio,
whenever the run recorded pserver traffic); --trace PATH writes the
unified chrome://tracing timeline (open in chrome://tracing or
perfetto).

Live-process mode (fluid-pulse):

    python tools/telemetry_dump.py --url http://host:port [--format ...]

reads a RUNNING process's pulse endpoint instead of running the local
demo loop: `--format prom` prints its `/metrics` scrape verbatim,
`json`/`table` render its `/status` document — the SAME shape the
in-process path prints, so one tool reads dead and live processes.

Multi-process stitch (fluid-xray):

    python tools/telemetry_dump.py --merge merged.json t0.json ps0.json

merges per-process trace files (each written by `Tracer.export_chrome`
in its own process, with its real pid + process_name metadata) into ONE
timeline. Exit 1 if any span would be dropped — a merge that loses
spans is a broken postmortem. Client and server halves of one RPC share
a trace id (`args.trace_id`), so the merged file shows the cross-process
call tree.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(fluid):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        pred = fluid.layers.fc(input=h, size=4, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def print_status_table(doc):
    """Human summary of a status document — shared by the in-process
    path and `--url` (identical output for identical telemetry)."""
    from paddle_tpu.wire import wire_table_from_snapshot

    steps = doc["steps"]
    print(f"steps: {steps['steps']}  "
          f"mean {steps['mean_step_us']:.1f} us/step")
    for phase, us in sorted(steps["phase_us"].items(),
                            key=lambda kv: -kv[1]):
        print(f"  {phase:<16} {us:>12.1f} us total")
    print("recompiles:", doc["recompiles"]["counts"] or "none")
    mem = doc.get("memory") or {}
    if mem.get("programs"):
        print(f"memory: peak est {mem['estimate_peak_bytes'] / 1e6:.2f} MB "
              f"over {len(mem['programs'])} program(s)"
              + (f", live {mem['bytes_in_use'] / 1e6:.2f} MB in use"
                 if mem.get("live") else " (estimate-only: no device "
                 "memory stats on this backend)"))
    alerts = doc.get("alerts") or []
    if alerts:
        print(f"ALERTS ({len(alerts)} active):")
        for a in alerts:
            print(f"  [{a['rule']}] {a['message']}")
    else:
        print("alerts: none")
    for line in wire_table_from_snapshot(doc["metrics"]):
        print(line)
    print("metrics:", ", ".join(sorted(doc["metrics"])))


def _fetch(url: str, timeout: float = 10.0):
    """(status, body) — or (None, error string) when the process is
    unreachable (dead, refused, timed out): the common case for a tool
    that exists to read live processes must exit cleanly, not
    traceback."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except (urllib.error.URLError, OSError) as e:
        return None, str(e)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="dump fluid-scope telemetry of a short prepared run")
    ap.add_argument("--url", metavar="http://host:port",
                    help="read a LIVE process's pulse endpoint instead of "
                         "running the local demo loop")
    ap.add_argument("--steps", type=int, default=3,
                    help="training steps to run (default 3)")
    ap.add_argument("--two-shapes", action="store_true",
                    help="alternate two batch sizes (provokes a "
                         "feed_shape recompile)")
    ap.add_argument("--assert-no-recompiles", action="store_true",
                    help="exit 1 if any compile event beyond first_call "
                         "was recorded (CI gate)")
    ap.add_argument("--format", choices=("json", "prom", "table"),
                    default="json")
    ap.add_argument("--trace", metavar="PATH",
                    help="also write the chrome://tracing timeline here")
    ap.add_argument("--merge", metavar="OUT",
                    help="stitch per-process chrome trace files (the "
                         "positional args) into OUT and exit; exit 1 if "
                         "the merge would drop spans")
    ap.add_argument("inputs", nargs="*",
                    help="input trace files for --merge")
    args = ap.parse_args(argv)

    if args.merge:
        from paddle_tpu.observe.tracer import merge_chrome_traces
        if not args.inputs:
            print("--merge needs at least one input trace file",
                  file=sys.stderr)
            return 1
        doc, stats = merge_chrome_traces(args.inputs, out_path=args.merge)
        print(json.dumps(stats, indent=2, sort_keys=True))
        if stats["spans_out"] != stats["spans_in"]:
            print(f"MERGE DROPPED SPANS: {stats['spans_in']} in, "
                  f"{stats['spans_out']} out", file=sys.stderr)
            return 1
        print(f"merged {stats['spans_in']} spans from "
              f"{len(args.inputs)} file(s) -> {args.merge}",
              file=sys.stderr)
        return 0

    if args.url:
        base = args.url.rstrip("/")
        if args.format == "prom":
            code, body = _fetch(f"{base}/metrics")
            if code != 200:
                print(f"GET {base}/metrics -> "
                      f"{code if code is not None else body}",
                      file=sys.stderr)
                return 1
            sys.stdout.write(body.decode())
            return 0
        code, body = _fetch(f"{base}/status")
        if code != 200:
            print(f"GET {base}/status -> "
                  f"{code if code is not None else body}", file=sys.stderr)
            return 1
        doc = json.loads(body)
        if args.format == "table":
            print_status_table(doc)
        else:
            print(json.dumps(doc, indent=2, sort_keys=True, default=str))
        return 0

    import jax
    jax.config.update("jax_platforms", "cpu")  # env var alone is overridden

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import observe

    fluid.set_flag("observe", True)

    main_p, startup, loss = build_model(fluid)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    prepared = exe.prepare(main_p, fetch_list=[loss], scope=scope)

    rng = np.random.RandomState(0)
    batch_sizes = (8, 12) if args.two_shapes else (8,)
    for i in range(max(args.steps, 1)):
        bs = batch_sizes[i % len(batch_sizes)]
        prepared.run({"x": rng.randn(bs, 16).astype(np.float32),
                      "y": rng.randint(0, 4, (bs, 1)).astype(np.int64)})

    reg = observe.default_registry()
    obsv = observe.observatory()

    if args.format == "prom":
        print(reg.to_prometheus())
    else:
        # the in-process document is pulse.status_document(): identical
        # in shape to a live /status scrape, so --url and the local demo
        # render through the SAME printers. Built only on these branches
        # — it evaluates detectors and probes device memory, side
        # effects a prom scrape must not pay for. json_safe keeps the
        # local json output strict-parseable (and byte-compatible with
        # the --url path) when a metric or alert carries NaN/inf.
        from paddle_tpu.observe.flight import json_safe
        doc = json_safe(observe.pulse.status_document())
        if args.format == "table":
            print_status_table(doc)
        else:
            print(json.dumps(doc, indent=2, sort_keys=True, default=str))

    if args.trace:
        observe.get_tracer().export_chrome(args.trace)
        print(f"chrome trace written to {args.trace}", file=sys.stderr)

    if args.assert_no_recompiles:
        bad = obsv.unexpected()
        if bad:
            causes = sorted({e.cause for e in bad})
            print(f"ASSERT-NO-RECOMPILES FAILED: {len(bad)} recompile "
                  f"event(s) beyond first_call, cause(s): "
                  f"{', '.join(causes)}", file=sys.stderr)
            for e in bad:
                print(f"  {e!r} detail={e.detail}", file=sys.stderr)
            return 1
        print("assert-no-recompiles: OK (every program compiled exactly "
              "once)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
