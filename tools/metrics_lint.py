#!/usr/bin/env python
"""fluid-horizon metrics-catalog drift lint.

Every metric name the codebase can emit through `observe.metrics` must
have a row in the "## Metric catalog" table of docs/OBSERVABILITY.md —
and every catalog row should still correspond to an emitter. Metrics
are an interface: dashboards, the observatory's derived series, and
alert rules key on these names, so a rename that skips the catalog is
a silent break for every consumer. The lint runs as a tier-1 test
(tests/test_tools.py) exactly like the race_lint repo gate.

Emitted names are discovered statically:

  * string-literal first arguments of ``counter(`` / ``gauge(`` /
    ``histogram(`` call sites (any receiver, newlines tolerated), and
  * module-level ``*_METRIC = "..."`` / ``*_SERIES = "..."`` constants
    (the repo's idiom for names shared between emitter and tests).

Names built dynamically (f-strings, concatenation) are invisible to
the scan; keep metric names literal — that is the point of a catalog.

Exit status: 0 = clean (stale catalog rows only warn), 1 = emitted
metric missing from the catalog (or --strict and warnings), 2 = usage
failure.  `--list` prints the discovered emitted names and exits.
"""

from __future__ import annotations

import argparse
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CATALOG_HEADING = "## Metric catalog"

# string-literal first argument of a counter/gauge/histogram call.
# The receiver is irrelevant (self._metrics.counter, reg.gauge, ...);
# requiring the '(' to follow the method name directly keeps matches
# honest, and \s* tolerates a line break before the literal.
_CALL_RE = re.compile(
    r"\b(?:counter|gauge|histogram)\(\s*[\"']([a-z][a-z0-9_]*)[\"']")

# ALERTS_METRIC = "health_alerts_total" / UP_SERIES = "horizon_up"
_CONST_RE = re.compile(
    r"^\s*[A-Z][A-Z0-9_]*(?:_METRIC|_SERIES)\s*=\s*[\"']"
    r"([a-z][a-z0-9_]*)[\"']", re.M)

# catalog table row: | `name` | kind | source | description |
_ROW_RE = re.compile(r"^\|\s*`([a-z][a-z0-9_]*)`\s*\|", re.M)


def scan_emitted(pkg_root: str) -> dict:
    """Map of metric name -> sorted list of repo-relative files that
    can emit it."""
    emitted: dict = {}
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            rel = os.path.relpath(path, REPO)
            for rx in (_CALL_RE, _CONST_RE):
                for name in rx.findall(text):
                    emitted.setdefault(name, set()).add(rel)
    return {k: sorted(v) for k, v in emitted.items()}


def parse_catalog(doc_path: str):
    """Names from the catalog table, plus whether the section exists."""
    try:
        with open(doc_path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        raise SystemExit(f"cannot read {doc_path!r}: {e}")
    start = text.find(CATALOG_HEADING)
    if start < 0:
        return None
    # section runs until the next heading of depth <= 2
    m = re.search(r"^#{1,2} ", text[start + len(CATALOG_HEADING):], re.M)
    section = text[start:] if m is None \
        else text[start:start + len(CATALOG_HEADING) + m.start()]
    return set(_ROW_RE.findall(section))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="metrics_lint",
        description="catalog drift check: emitted metric names vs the "
                    "docs/OBSERVABILITY.md metric catalog")
    ap.add_argument("--doc", default=os.path.join(REPO, "docs",
                                                  "OBSERVABILITY.md"))
    ap.add_argument("--pkg", default=os.path.join(REPO, "paddle_tpu"))
    ap.add_argument("--list", action="store_true",
                    help="print emitted names with their source files")
    ap.add_argument("--strict", action="store_true",
                    help="stale catalog rows fail too")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.pkg):
        raise SystemExit(f"package root {args.pkg!r} is not a directory")
    emitted = scan_emitted(args.pkg)

    if args.list:
        for name in sorted(emitted):
            print(f"{name}  ({', '.join(emitted[name])})")
        return 0

    catalog = parse_catalog(args.doc)
    if catalog is None:
        print(f"ERROR: {os.path.relpath(args.doc, REPO)} has no "
              f"{CATALOG_HEADING!r} section")
        return 1

    missing = sorted(set(emitted) - catalog)
    stale = sorted(catalog - set(emitted))

    for name in missing:
        print(f"ERROR: emitted metric `{name}` missing from catalog "
              f"({', '.join(emitted[name])})")
    for name in stale:
        print(f"WARNING: catalog row `{name}` has no emitter "
              f"(renamed or removed?)")

    print(f"metrics_lint: {len(emitted)} emitted, {len(catalog)} "
          f"cataloged, {len(missing)} missing, {len(stale)} stale")
    return 1 if (missing or (args.strict and stale)) else 0


if __name__ == "__main__":
    sys.exit(main())
