#!/usr/bin/env python
"""quorum_node: run one fluid-quorum arbiter as its own process.

    python tools/quorum_node.py --endpoint 127.0.0.1:0 --data-dir /var/q \
        [--node-id n0] [--status RESOURCE]

A production quorum is 3 (or 5) of these on separate failure domains;
tests and the chaos drills run them in-process instead (the rpc fault
hook — the partition injector — only reaches in-process messages).

Prints "ENDPOINT <host:port>" once listening (ephemeral-port friendly),
then parks until SIGTERM/SIGINT, which stops the node cleanly — its
persisted epoch file (`<data-dir>/<node-id>_quorum_epochs.json`, ark
atomic-write + sha256 sidecar) survives the restart and the node
re-opens under a boot blackout sized to the longest lease it ever
granted, so a crashed arbiter can never regress an epoch or hand a
rival a too-early vote.

`--status RESOURCE` (no server): print the node's persisted epoch and
exit — the operator's "which epoch did this arbiter promise" probe.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--endpoint", default="127.0.0.1:0")
    ap.add_argument("--data-dir", required=True,
                    help="dir for the persisted epoch file")
    ap.add_argument("--node-id", default=None,
                    help="stable node identity (default: derived from "
                         "the bound port — pass one explicitly when the "
                         "endpoint uses port 0 and restarts must find "
                         "the same epoch file)")
    ap.add_argument("--status", metavar="RESOURCE", default=None,
                    help="print the persisted epoch for RESOURCE and "
                         "exit (no server)")
    args = ap.parse_args(argv)

    from paddle_tpu.quorum import QuorumNode, QuorumStore

    if args.status is not None:
        store = QuorumStore(args.data_dir, args.node_id or "q0")
        print(f"{args.status} epoch={store.epoch(args.status)} "
              f"lease_s={store.lease_s(args.status)}")
        return 0

    node = QuorumNode(args.endpoint, args.data_dir,
                      node_id=args.node_id).start()
    print(f"ENDPOINT {node.endpoint}", flush=True)

    done = threading.Event()

    def _stop(signum, frame):
        node.stop()
        done.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    done.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
