#!/usr/bin/env python
"""fluid-lint: static verification CLI over serialized Programs and book
models.

    # lint a serialized program (Program.serialize_to_string JSON)
    python tools/paddle_lint.py /path/to/program.json

    # lint a model-zoo graph, with (default) or without its training ops
    python tools/paddle_lint.py --model mnist
    python tools/paddle_lint.py --model transformer --no-train

    # machine-readable findings
    python tools/paddle_lint.py --format json program.json

Exit status: 0 = clean (or warnings only), 1 = ERROR-severity findings,
2 = usage/load failure. `--strict` promotes warnings to the failing set.

The sweep is `paddle_tpu.analysis.analyze_program`: structural verifier,
whole-program shape/dtype cross-check, and TPU lints (float64 use, dead
ops relative to fetch targets, feed-shape recompile hazards). Fetch
targets default to the model's declared fetches; pass --fetch for
serialized programs so the dead-op lint has roots.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the lint sweep is abstract (eval_shape only) — never initialize a TPU
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _load_model(name: str, train: bool):
    import paddle_tpu as fluid
    from paddle_tpu import models

    mod = getattr(models, name, None)
    if mod is None or not hasattr(mod, "build"):
        known = sorted(m for m in dir(models)
                       if hasattr(getattr(models, m), "build"))
        raise SystemExit(f"unknown model {name!r}; known: {known}")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        feeds, fetches = _small_build(mod, name)
        if train:
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(
                fetches["loss"])
    return (main, sorted(feeds), [v.name for v in fetches.values()])


def _small_build(mod, name: str):
    """Small shapes where the default config is benchmark-sized: the lint
    is structural, and a dict_size=30000 embedding adds nothing but
    eval_shape time."""
    small = {
        "resnet": dict(class_dim=10, depth=50, image_shape=(3, 64, 64)),
        "se_resnext": dict(class_dim=10, image_shape=(3, 64, 64)),
        "vgg": dict(class_dim=10, image_shape=(3, 32, 32)),
        "stacked_dynamic_lstm": dict(dict_size=200, emb_dim=16,
                                     hidden_dim=16, stacked_num=2),
        "machine_translation": dict(dict_size=200, emb_dim=16,
                                    hidden_dim=16),
        "deepfm": dict(num_fields=8, sparse_feature_dim=1000,
                       embedding_size=8),
    }
    return mod.build(**small.get(name, {}))


def _load_json(path: str):
    from paddle_tpu.core import ir

    try:
        with open(path) as f:
            prog = ir.Program.parse_from_string(f.read())
    except (OSError, json.JSONDecodeError, KeyError) as e:
        raise SystemExit(f"cannot load program from {path!r}: {e}")
    feeds = sorted(v.name for v in prog.global_block().vars.values()
                   if v.is_data)
    return prog, feeds, None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_lint",
        description="static verifier + shape inference + TPU lints over "
                    "the Program IR")
    ap.add_argument("program", nargs="?",
                    help="serialized program JSON (Program.serialize_to_string)")
    ap.add_argument("--model", help="lint a paddle_tpu.models graph instead")
    ap.add_argument("--no-train", action="store_true",
                    help="with --model: skip optimizer.minimize (lint the "
                         "forward graph only)")
    ap.add_argument("--fetch", action="append", default=None, metavar="VAR",
                    help="fetch target(s) anchoring the dead-op lint")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on warnings too")
    ap.add_argument("--no-lint", action="store_true",
                    help="structural verification + shapes only")
    args = ap.parse_args(argv)

    if bool(args.program) == bool(args.model):
        ap.error("pass exactly one of: a program JSON path, or --model NAME")

    if args.model:
        program, feeds, fetches = _load_model(args.model,
                                              train=not args.no_train)
    else:
        program, feeds, fetches = _load_json(args.program)
    if args.fetch:
        fetches = list(args.fetch)

    from paddle_tpu import analysis

    diags = analysis.analyze_program(program, feed_targets=feeds,
                                     fetch_targets=fetches,
                                     lint=not args.no_lint)
    n_err = sum(d.severity == analysis.Severity.ERROR for d in diags)
    n_warn = sum(d.severity == analysis.Severity.WARNING for d in diags)

    if args.format == "json":
        print(json.dumps({"errors": n_err, "warnings": n_warn,
                          "diagnostics": [d.to_dict() for d in diags]},
                         indent=2))
    else:
        target = args.model or args.program
        if diags:
            print(analysis.format_diagnostics(diags))
        print(f"{target}: {n_err} error(s), {n_warn} warning(s), "
              f"{len(diags) - n_err - n_warn} note(s)")
    return 1 if (n_err or (args.strict and n_warn)) else 0


if __name__ == "__main__":
    sys.exit(main())
