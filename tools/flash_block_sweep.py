"""Block-size sweep for the streamed flash kernels (round-4 item 5).

Times fwd+bwd of flash_attention directly (same-process interleaved,
two-point slope) for BQ x BK combinations at transformer-shaped sizes.

Usage: python tools/flash_block_sweep.py [--seq 4096] [--causal]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

COMBOS = [(256, 256), (256, 512), (512, 256), (512, 512),
          (512, 1024), (1024, 512), (1024, 1024),
          (256, 1024), (512, 2048), (256, 2048), (128, 1024)]


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import pallas_attention as pa

    seq = 4096
    causal = "--causal" in sys.argv
    for i, a in enumerate(sys.argv):
        if a == "--seq":
            seq = int(sys.argv[i + 1])

    B, H, D = 4, 8, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, seq, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, seq, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, seq, D), jnp.bfloat16)
    seed = jnp.int32(0)

    N_CHAIN = 16

    def make_step(bq, bk):
        pa._BLOCK_OVERRIDE = (bq, bk)

        def f(q, k, v):
            o = pa.flash_attention(q, k, v, seed, causal,
                                   1.0 / np.sqrt(D), 0.0)
            return jnp.sum(o.astype(jnp.float32))

        @jax.jit
        def step(q, k, v):
            # chain N fwd+bwd passes inside one jit (the grads feed the
            # next iteration, so nothing can be CSE'd away) — per-call
            # device time is big enough to dwarf tunnel jitter
            def body(c, _):
                q, k, v = c
                l, (dq, dk, dv) = jax.value_and_grad(
                    f, argnums=(0, 1, 2))(q, k, v)
                eps = jnp.asarray(1e-3, q.dtype)
                return (q - eps * dq, k - eps * dk, v - eps * dv), l
            (q, k, v), ls = jax.lax.scan(body, (q, k, v), None,
                                         length=N_CHAIN)
            return ls.sum()
        return step

    print(f"seq={seq} causal={causal} B={B} H={H} D={D}")
    for bq, bk in COMBOS:
        if seq % bq or seq % bk:
            continue
        try:
            step = make_step(bq, bk)
            np.asarray(step(q, k, v))  # compile
        except Exception as e:
            print(f"BQ{bq} x BK{bk}: FAILED ({type(e).__name__})")
            continue

        def window(n):
            t0 = time.perf_counter()
            for _ in range(n):
                out = step(q, k, v)
            np.asarray(out)
            return time.perf_counter() - t0

        slopes = []
        for _ in range(3):
            t_lo, t_hi = window(1), window(3)
            slopes.append((t_hi - t_lo) / 2)
        dt = sorted(slopes)[1] / N_CHAIN
        # fwd 2*T^2*D*2 (qk + pv) + bwd ~2.5x fwd matmul flops, per head
        flops = B * H * (2 * seq * seq * D * 2) * 3.5
        if causal:
            flops /= 2
        print(f"BQ{bq} x BK{bk}: {dt * 1e3:7.2f} ms  "
              f"~{flops / dt / 1e12:5.1f} TFLOP/s")
    pa._BLOCK_OVERRIDE = None


if __name__ == "__main__":
    main()
